"""WriteStats: the write-side twin of pipeline.PipelineStats.

The read path attributes a slow scan to a lane (io / decompress / stage /
...) through the registry ``pipeline`` section; until this module a slow
WRITE was a black box — encode, compress, and sink flushes all hid inside
one wall clock.  WriteStats splits the writer into the three lanes the
sharded writer actually overlaps, plus the two dataset-level passes:

- ``encode``    value encoding + page cutting + dictionary build (CPU,
                compress excluded — the ChunkEncoder subtracts it)
- ``compress``  the codec passes over page payloads (GIL-released for
                snappy/zlib, so worker threads genuinely overlap here)
- ``flush``     sink writes: page parts, footers, fsync at publish
- ``merge``     footer-merge stitching (relocation + span copies)
- ``compact``   compaction passes (decode + re-batch bookkeeping)

``as_dict()`` feeds ``StatsRegistry.add_write`` (the registry ``write``
section, golden-key-tested like every other section) so ``pq_tool
doctor`` can attribute a slow write the way it already attributes a slow
read.  Each ``timed`` stage also emits a ``write.<stage>`` span on the
process tracer, so ``TPQ_TRACE`` artifacts show writer lanes in Perfetto.
"""

from __future__ import annotations

import itertools
import threading
import time
from contextlib import contextmanager

from ..obs import (LatencyHistogram, current_tracer, register_flight_source)

__all__ = ["WriteStats", "WRITE_STAGES"]

WRITE_STAGES = ("encode", "compress", "flush", "merge", "compact")

# per-instance flight-source token (several writers can be live at once —
# a dump must show each one's lanes, same discipline as PipelineStats)
_wstats_ids = itertools.count(1)


class WriteStats:
    """Per-stage timing + throughput counters for the write path.

    Thread-safe: the sharded writer's encode workers and its file-writer
    consumer add concurrently.  ``stall_seconds`` counts submitter time
    blocked on the in-flight memory budget (backpressure, exactly the
    read pipeline's meaning).  ``merge_from`` composes (a compaction run
    folds its member writers' stats into one report).
    """

    def __init__(self, workers: int = 0, tracer=None):
        self.workers = int(workers)
        self.rows = 0
        self.row_groups = 0
        self.chunks = 0
        self.files = 0
        self.bytes_written = 0
        self.stall_seconds = 0.0
        self.wall_seconds = 0.0
        self._stage_seconds = {s: 0.0 for s in WRITE_STAGES}
        self._stage_hist = {s: LatencyHistogram() for s in WRITE_STAGES}
        self.tracer = tracer if tracer is not None else current_tracer()
        self._lock = threading.Lock()
        self._t0 = None
        register_flight_source(f"write[{next(_wstats_ids)}]", self, "sample")

    # -- accumulation ---------------------------------------------------------

    def add(self, stage: str, seconds: float) -> None:
        if stage not in self._stage_seconds:
            raise ValueError(
                f"unknown write stage {stage!r}; valid stages: "
                f"{', '.join(WRITE_STAGES)}")
        with self._lock:
            self._stage_seconds[stage] += seconds
        self._stage_hist[stage].record(seconds)

    @contextmanager
    def timed(self, stage: str, **span_args):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            self.add(stage, t1 - t0)
            tr = self.tracer
            if tr is not None and tr.active:
                tr.complete(f"write.{stage}", t0, t1, **span_args)

    def add_stall(self, seconds: float) -> None:
        with self._lock:
            self.stall_seconds += seconds

    def count_row_group(self, rows: int, chunks: int = 0) -> None:
        with self._lock:
            self.row_groups += 1
            self.rows += int(rows)
            self.chunks += int(chunks)

    def count_file(self, nbytes: int) -> None:
        with self._lock:
            self.files += 1
            self.bytes_written += int(nbytes)

    def touch_wall(self) -> None:
        now = time.perf_counter()
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            self.wall_seconds = now - self._t0

    # -- composition ----------------------------------------------------------

    def merge_from(self, other: "WriteStats") -> None:
        """Fold another writer's counters in: seconds/counts add, workers
        max (the compose case is member writers of one dataset run), the
        wall clock stays this object's own."""
        with other._lock:
            stages = dict(other._stage_seconds)
            vals = (other.rows, other.row_groups, other.chunks, other.files,
                    other.bytes_written, other.stall_seconds, other.workers)
        with self._lock:
            for s, v in stages.items():
                self._stage_seconds[s] += v
            (rows, rgs, chunks, files, bw, stall, workers) = vals
            self.rows += rows
            self.row_groups += rgs
            self.chunks += chunks
            self.files += files
            self.bytes_written += bw
            self.stall_seconds += stall
            self.workers = max(self.workers, workers)
        for s in WRITE_STAGES:
            self._stage_hist[s].merge_from(other._stage_hist[s])

    # -- reporting ------------------------------------------------------------

    def stage_seconds(self, stage: str) -> float:
        with self._lock:
            return self._stage_seconds[stage]

    @property
    def busy_seconds(self) -> float:
        with self._lock:
            return sum(self._stage_seconds.values())

    def sample(self) -> dict:
        """Point-in-time snapshot for the flight recorder / Sampler: the
        cumulative per-stage seconds plus live progress counters."""
        with self._lock:
            out = {s: round(v, 6) for s, v in self._stage_seconds.items()}
            out["rows"] = self.rows
            out["row_groups"] = self.row_groups
            out["bytes_written"] = self.bytes_written
        return out

    def as_dict(self) -> dict:
        with self._lock:
            stages = {f"{s}_seconds": round(v, 6)
                      for s, v in self._stage_seconds.items()}
            out = {
                "workers": self.workers,
                "rows": self.rows,
                "row_groups": self.row_groups,
                "chunks": self.chunks,
                "files": self.files,
                "bytes_written": self.bytes_written,
                **stages,
                "stall_seconds": round(self.stall_seconds, 6),
                "wall_seconds": round(self.wall_seconds, 6),
            }
        out["busy_seconds"] = round(self.busy_seconds, 6)
        # only the stages that saw work (same artifact-size discipline as
        # PipelineStats.as_dict)
        out["stage_histograms"] = {s: h.as_dict()
                                   for s, h in self._stage_hist.items()
                                   if h.count}
        return out
