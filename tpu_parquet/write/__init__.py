"""tpu_parquet.write: the write side of scale (ROADMAP direction 5).

Three layers over the low-level :class:`~tpu_parquet.writer.FileWriter`:

- :func:`write_sharded` — N workers encode disjoint row-group sets
  through the existing ``FileWriter``/``chunk_encode`` path (the
  reference's L4 chunk writers), one footer-merge consumer stitches a
  single file or a manifest-indexed file set (the L6 file writer);
- :mod:`~tpu_parquet.write.merge` / :mod:`~tpu_parquet.write.manifest` —
  the footer-merge math (pure, fuzzed) and the versioned atomic-publish
  manifest readers consume as one dataset;
- :func:`compact` / :class:`CompactionService` — many small files → few
  large, codec re-planned through the ship planner so compacted output
  is cheap to ship back to the device, CRCs always written, atomic
  publish + generation bump so concurrent readers never see a torn or
  stale dataset.

Observability rides :class:`WriteStats` into the registry ``write``
section (``pq_tool doctor`` attributes slow writes); ``TPQ_WRITE_CRC``
(default ON) mirrors the reader's ``TPQ_VALIDATE`` contract.
"""

from .compact import (CompactionReport, CompactionService, compact,
                      modeled_link_bytes, plan_codec)
from .manifest import (MANIFEST_NAME, MANIFEST_VERSION, Manifest,
                       ManifestEntry, expand_dataset, find_manifest,
                       load_manifest, write_manifest)
from .merge import merge_files, merge_footers, validate_shard_footer
from .sharded import (ShardedWriteResult, encode_row_group,
                      resolve_write_workers, write_sharded)
from .stats import WRITE_STAGES, WriteStats

__all__ = [
    "WriteStats", "WRITE_STAGES",
    "write_sharded", "encode_row_group", "ShardedWriteResult",
    "resolve_write_workers",
    "merge_files", "merge_footers", "validate_shard_footer",
    "Manifest", "ManifestEntry", "MANIFEST_NAME", "MANIFEST_VERSION",
    "write_manifest", "load_manifest", "find_manifest", "expand_dataset",
    "compact", "CompactionReport", "CompactionService",
    "plan_codec", "modeled_link_bytes",
]
