"""Unified error hierarchy: every malformed-input failure is a ParquetError.

The reference turns every internal panic into one error type at its public
boundary (FileReader.recover, file_reader.go:177-184; schemaParser.recover,
schema_parser.go:285-298).  The Python equivalent is subclassing: each layer
keeps its specific error (ThriftError, RLEError, ...), all rooted here, so
callers — and the fuzz harness's crash oracle — catch exactly one type.
"""


class ParquetError(ValueError):
    """Malformed parquet input."""


class HangError(RuntimeError):
    """A watched pipeline made no progress within the watchdog deadline.

    Raised by :class:`tpu_parquet.obs.Watchdog` (policy ``raise``) in the
    SUBMITTING thread — the one blocked on an
    :class:`~tpu_parquet.alloc.InFlightBudget` — after a flight-recorder
    dump has been written, so the wedge becomes a diagnosable error instead
    of a silent hang.  Deliberately NOT a ParquetError: the input file is
    not malformed, the pipeline is stuck, and the fuzz harness's
    crash oracle must never classify a hang as a parse failure.
    ``dump_path`` names the flight-recorder snapshot to feed
    ``pq_tool autopsy``.
    """

    def __init__(self, message: str, dump_path: "str | None" = None):
        super().__init__(message)
        self.dump_path = dump_path


class TransientIOError(IOError):
    """A single range-read attempt failed in a way that is worth retrying.

    Raised by :class:`tpu_parquet.iostore.ByteStore` implementations (and
    the fault injector) for the failure modes real object stores exhibit —
    connection resets, throttling, torn/short responses, per-attempt
    deadline overruns.  ``GenericRangeStore.read_range`` catches it and
    retries with backoff; it only escapes to callers wrapped in a
    :class:`RetryExhaustedError`.  Rooted at ``IOError`` (NOT ParquetError):
    the input file is fine, the transport hiccuped, and the fuzz harness's
    crash oracle must never read a network fault as a parse failure.
    """


class RetryExhaustedError(IOError):
    """A range read failed after exhausting its retries / deadline / budget.

    Raised by :class:`tpu_parquet.iostore.GenericRangeStore` when a read's
    bounded retries, its per-request deadline (``TPQ_IO_DEADLINE_S``), or
    the per-scan retry budget (``TPQ_IO_RETRY_BUDGET``) runs out.
    ``attempts`` carries the full attempt log (one dict per try: error,
    elapsed, backoff) so the error itself is the diagnosis; ``offset`` /
    ``size`` name the range that could not be read.  Rooted at ``IOError``,
    not ParquetError — the bytes were never readable, nothing was malformed.
    """

    def __init__(self, message: str, attempts: "list | None" = None,
                 offset: "int | None" = None, size: "int | None" = None):
        super().__init__(message)
        self.attempts = list(attempts or [])
        self.offset = offset
        self.size = size


class OverloadError(RuntimeError):
    """A scan service rejected a request because its admission queue is full
    — or shed it under brownout (``TPQ_SERVE_BROWNOUT``).

    Raised by :class:`tpu_parquet.serve.ScanService` *at submission time* —
    a fast-reject, never a blocked caller: under overload the service sheds
    load in microseconds so callers can back off or route elsewhere, instead
    of queueing unboundedly until every client times out.  Deliberately NOT
    a ParquetError (nothing is malformed) and not an IOError (nothing was
    read): it is a load-shedding signal.  ``queue_depth`` and ``in_flight``
    carry the admission state at rejection so the error itself says how
    overloaded the service was; ``retry_after_s`` (brownout sheds) is the
    service's drain-rate-derived back-off hint, and ``shed_priority`` names
    the priority band that was shed (None for a plain queue-full reject).
    """

    def __init__(self, message: str, queue_depth: "int | None" = None,
                 in_flight: "int | None" = None,
                 retry_after_s: "float | None" = None,
                 shed_priority: "int | None" = None):
        super().__init__(message)
        self.queue_depth = queue_depth
        self.in_flight = in_flight
        self.retry_after_s = retry_after_s
        self.shed_priority = shed_priority


class DeadlineExceededError(TimeoutError):
    """A request's end-to-end deadline expired before it finished.

    Raised for the ONE caller whose :class:`tpu_parquet.serve.ScanRequest`
    carried ``deadline_s`` (the deadline rides the scan's
    :class:`~tpu_parquet.resilience.CancelToken` into every
    ``ByteStore.read_range`` and is checked at unit boundaries in the
    prefetch pipeline): the request stops issuing new IO, frees its
    admission-budget charge, and surfaces here — no other request notices.
    Rooted at TimeoutError (generic timeout handling catches it), NOT
    ParquetError (nothing is malformed) and NOT IOError (the transport is
    fine; the caller's clock ran out).  ``deadline_s`` echoes the budget
    the request was given.
    """

    def __init__(self, message: str, deadline_s: "float | None" = None):
        super().__init__(message)
        self.deadline_s = deadline_s


class CancelledError(RuntimeError):
    """The caller cancelled its own request (``ScanTicket.cancel()``).

    Same containment contract as :class:`DeadlineExceededError` — the
    cancelled request stops issuing new IO at the next unit boundary and
    releases what it held, everyone else is untouched.  A distinct type
    from ``concurrent.futures.CancelledError`` on purpose: this is an
    application-level verdict delivered through ``ticket.result()``, and
    the fuzz oracle / retry machinery must never confuse it with a pool
    internals error.
    """


class CircuitOpenError(RuntimeError):
    """A per-file circuit breaker is open: the file is failing repeatedly
    and requests touching it fast-fail instead of re-paying the full
    retry/deadline cost.

    Raised by :class:`tpu_parquet.serve.ScanService` before any byte of the
    named file is read, once :class:`~tpu_parquet.resilience.BreakerBoard`
    has seen N classified failures inside its window (``TPQ_CIRCUIT_FAILS``
    / ``TPQ_CIRCUIT_WINDOW_S``).  ``file`` names the poisoned file,
    ``retry_after_s`` the cooldown remaining until a half-open probe is
    admitted.  NOT a ParquetError: the file MAY be malformed, but this
    error reports the breaker's memory of earlier failures, not a fresh
    diagnosis — the original classified error is what said why.
    """

    def __init__(self, message: str, file: "str | None" = None,
                 retry_after_s: "float | None" = None):
        super().__init__(message)
        self.file = file
        self.retry_after_s = retry_after_s


class DataIntegrityError(ParquetError):
    """A scan's data-error budget is exhausted: corruption is no longer
    containable.

    Raised by :class:`tpu_parquet.quarantine.Quarantine` when the number of
    contained data errors exceeds the budget (``TPQ_DATA_ERROR_BUDGET``:
    absolute count and fraction-of-units) — a file set failing *everywhere*
    must abort the run with the full evidence, not silently skip itself to
    an empty epoch.  ``records`` carries the structured quarantine records
    (one dict per failure: file, row group, column, page, offset, error
    class, message) noted during the scan, so the error itself is the
    complete diagnosis.  Rooted at ParquetError: the input data really is
    malformed, and the fuzz harness's crash oracle should classify it so.
    """

    def __init__(self, message: str, records: "list | None" = None):
        super().__init__(message)
        self.records = list(records or [])


class CheckpointError(ParquetError):
    """Malformed, incompatible, or version-mismatched loader checkpoint state.

    Raised by ``tpu_parquet.data.checkpoint`` for any state blob that cannot
    be adopted safely — truncation, bad magic, unknown version, type/range
    violations, and dataset-fingerprint mismatches all land here rather than
    silently mis-seeking the loader.  Rooted at ParquetError so the fuzz
    harness's single-type crash oracle covers the checkpoint surface too.
    """
