"""Unified error hierarchy: every malformed-input failure is a ParquetError.

The reference turns every internal panic into one error type at its public
boundary (FileReader.recover, file_reader.go:177-184; schemaParser.recover,
schema_parser.go:285-298).  The Python equivalent is subclassing: each layer
keeps its specific error (ThriftError, RLEError, ...), all rooted here, so
callers — and the fuzz harness's crash oracle — catch exactly one type.
"""


class ParquetError(ValueError):
    """Malformed parquet input."""


class CheckpointError(ParquetError):
    """Malformed, incompatible, or version-mismatched loader checkpoint state.

    Raised by ``tpu_parquet.data.checkpoint`` for any state blob that cannot
    be adopted safely — truncation, bad magic, unknown version, type/range
    violations, and dataset-fingerprint mismatches all land here rather than
    silently mis-seeking the loader.  Rooted at ParquetError so the fuzz
    harness's single-type crash oracle covers the checkpoint surface too.
    """
