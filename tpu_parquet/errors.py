"""Unified error hierarchy: every malformed-input failure is a ParquetError.

The reference turns every internal panic into one error type at its public
boundary (FileReader.recover, file_reader.go:177-184; schemaParser.recover,
schema_parser.go:285-298).  The Python equivalent is subclassing: each layer
keeps its specific error (ThriftError, RLEError, ...), all rooted here, so
callers — and the fuzz harness's crash oracle — catch exactly one type.
"""


class ParquetError(ValueError):
    """Malformed parquet input."""


class HangError(RuntimeError):
    """A watched pipeline made no progress within the watchdog deadline.

    Raised by :class:`tpu_parquet.obs.Watchdog` (policy ``raise``) in the
    SUBMITTING thread — the one blocked on an
    :class:`~tpu_parquet.alloc.InFlightBudget` — after a flight-recorder
    dump has been written, so the wedge becomes a diagnosable error instead
    of a silent hang.  Deliberately NOT a ParquetError: the input file is
    not malformed, the pipeline is stuck, and the fuzz harness's
    crash oracle must never classify a hang as a parse failure.
    ``dump_path`` names the flight-recorder snapshot to feed
    ``pq_tool autopsy``.
    """

    def __init__(self, message: str, dump_path: "str | None" = None):
        super().__init__(message)
        self.dump_path = dump_path


class TransientIOError(IOError):
    """A single range-read attempt failed in a way that is worth retrying.

    Raised by :class:`tpu_parquet.iostore.ByteStore` implementations (and
    the fault injector) for the failure modes real object stores exhibit —
    connection resets, throttling, torn/short responses, per-attempt
    deadline overruns.  ``GenericRangeStore.read_range`` catches it and
    retries with backoff; it only escapes to callers wrapped in a
    :class:`RetryExhaustedError`.  Rooted at ``IOError`` (NOT ParquetError):
    the input file is fine, the transport hiccuped, and the fuzz harness's
    crash oracle must never read a network fault as a parse failure.
    """


class RetryExhaustedError(IOError):
    """A range read failed after exhausting its retries / deadline / budget.

    Raised by :class:`tpu_parquet.iostore.GenericRangeStore` when a read's
    bounded retries, its per-request deadline (``TPQ_IO_DEADLINE_S``), or
    the per-scan retry budget (``TPQ_IO_RETRY_BUDGET``) runs out.
    ``attempts`` carries the full attempt log (one dict per try: error,
    elapsed, backoff) so the error itself is the diagnosis; ``offset`` /
    ``size`` name the range that could not be read.  Rooted at ``IOError``,
    not ParquetError — the bytes were never readable, nothing was malformed.
    """

    def __init__(self, message: str, attempts: "list | None" = None,
                 offset: "int | None" = None, size: "int | None" = None):
        super().__init__(message)
        self.attempts = list(attempts or [])
        self.offset = offset
        self.size = size


class OverloadError(RuntimeError):
    """A scan service rejected a request because its admission queue is full.

    Raised by :class:`tpu_parquet.serve.ScanService` *at submission time* —
    a fast-reject, never a blocked caller: under overload the service sheds
    load in microseconds so callers can back off or route elsewhere, instead
    of queueing unboundedly until every client times out.  Deliberately NOT
    a ParquetError (nothing is malformed) and not an IOError (nothing was
    read): it is a load-shedding signal.  ``queue_depth`` and ``in_flight``
    carry the admission state at rejection so the error itself says how
    overloaded the service was.
    """

    def __init__(self, message: str, queue_depth: "int | None" = None,
                 in_flight: "int | None" = None):
        super().__init__(message)
        self.queue_depth = queue_depth
        self.in_flight = in_flight


class DataIntegrityError(ParquetError):
    """A scan's data-error budget is exhausted: corruption is no longer
    containable.

    Raised by :class:`tpu_parquet.quarantine.Quarantine` when the number of
    contained data errors exceeds the budget (``TPQ_DATA_ERROR_BUDGET``:
    absolute count and fraction-of-units) — a file set failing *everywhere*
    must abort the run with the full evidence, not silently skip itself to
    an empty epoch.  ``records`` carries the structured quarantine records
    (one dict per failure: file, row group, column, page, offset, error
    class, message) noted during the scan, so the error itself is the
    complete diagnosis.  Rooted at ParquetError: the input data really is
    malformed, and the fuzz harness's crash oracle should classify it so.
    """

    def __init__(self, message: str, records: "list | None" = None):
        super().__init__(message)
        self.records = list(records or [])


class CheckpointError(ParquetError):
    """Malformed, incompatible, or version-mismatched loader checkpoint state.

    Raised by ``tpu_parquet.data.checkpoint`` for any state blob that cannot
    be adopted safely — truncation, bad magic, unknown version, type/range
    violations, and dataset-fingerprint mismatches all land here rather than
    silently mis-seeking the loader.  Rooted at ParquetError so the fuzz
    harness's single-type crash oracle covers the checkpoint surface too.
    """
