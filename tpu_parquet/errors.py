"""Unified error hierarchy: every malformed-input failure is a ParquetError.

The reference turns every internal panic into one error type at its public
boundary (FileReader.recover, file_reader.go:177-184; schemaParser.recover,
schema_parser.go:285-298).  The Python equivalent is subclassing: each layer
keeps its specific error (ThriftError, RLEError, ...), all rooted here, so
callers — and the fuzz harness's crash oracle — catch exactly one type.
"""


class ParquetError(ValueError):
    """Malformed parquet input."""


class HangError(RuntimeError):
    """A watched pipeline made no progress within the watchdog deadline.

    Raised by :class:`tpu_parquet.obs.Watchdog` (policy ``raise``) in the
    SUBMITTING thread — the one blocked on an
    :class:`~tpu_parquet.alloc.InFlightBudget` — after a flight-recorder
    dump has been written, so the wedge becomes a diagnosable error instead
    of a silent hang.  Deliberately NOT a ParquetError: the input file is
    not malformed, the pipeline is stuck, and the fuzz harness's
    crash oracle must never classify a hang as a parse failure.
    ``dump_path`` names the flight-recorder snapshot to feed
    ``pq_tool autopsy``.
    """

    def __init__(self, message: str, dump_path: "str | None" = None):
        super().__init__(message)
        self.dump_path = dump_path


class CheckpointError(ParquetError):
    """Malformed, incompatible, or version-mismatched loader checkpoint state.

    Raised by ``tpu_parquet.data.checkpoint`` for any state blob that cannot
    be adopted safely — truncation, bad magic, unknown version, type/range
    violations, and dataset-fingerprint mismatches all land here rather than
    silently mis-seeking the loader.  Rooted at ParquetError so the fuzz
    harness's single-type crash oracle covers the checkpoint surface too.
    """
