"""Unified trace/metrics layer: span tracer + one stats registry (SURVEY §5.5).

The ROADMAP's two open perf items (the 1B ×-host re-bank, the plain_int64
gap) are unattributable from per-stage *sums* alone: ``PipelineStats`` says
how much total time decompression took, not WHEN each chunk was in which
stage or where the pipeline actually stalled — and ``ship.py`` bets on a
cost model whose predictions nothing ever checks against the measured lanes.
This module is the instrument every later perf PR reads first.  Three
pieces, all stdlib-only (imported by the innermost hot loops, so it must
never pull numpy/jax):

- :class:`Tracer` — a thread-safe structured span tracer (nestable spans,
  instant events, counters) exporting **Chrome trace-event JSON** that
  Perfetto / ``chrome://tracing`` load directly.  Near-zero overhead when
  disabled: ``span()`` returns a shared no-op context manager after one
  attribute check, and every other record call is a single ``if`` —
  guaranteed by the tier-1 overhead guard in tests/test_obs.py.
  Activation: ``TPQ_TRACE=<path>`` (process-global tracer, written at
  interpreter exit) or ``trace=`` kwargs on ``FileReader`` /
  ``DeviceFileReader`` / ``DataLoader`` / ``scan_files`` (per-object tracer,
  written when the object closes).

- :class:`LatencyHistogram` — log2-bucketed latency distribution,
  mergeable across threads AND processes (``as_dict``/``from_dict``
  round-trip), giving per-stage p50/p95 where the round-6 counters only
  had sums.  ``PipelineStats.add`` feeds one per stage.

- :class:`StatsRegistry` — the one versioned ``as_dict()`` tree composing
  ``PipelineStats`` (+ its histograms), ``ReaderStats`` (per-route ship
  decisions WITH the cost model's predicted lane seconds), ``LoaderStats``,
  and ``AllocTracker`` peaks.  ``ship_feedback()`` puts the planner's
  predicted seconds next to the measured link lane (staged bytes / stage
  seconds) — the direct ``TPQ_LINK_MBPS`` calibration signal.

``pq_tool trace <run.json>`` (cli/pq_tool.py) renders a trace into the
per-stage p50/p95 table, overlap efficiency, stall attribution, and
route-prediction error via :func:`trace_summary`, so a trace is useful
without a browser.

The crash/hang half (the black-box flight recorder) lives here too:

- :class:`FlightRecorder` — an always-on, bounded per-thread ring of the
  most recent trace events.  Every :class:`Tracer` — including the
  disabled no-``TPQ_TRACE`` singleton — tees its spans/instants/counters
  into the process recorder, so the last N seconds of every lane are
  recoverable from a hung or crashed process.  ``dump()`` writes a
  versioned JSON snapshot: ring events per thread, every Python thread's
  stack (``sys._current_frames``), live ``InFlightBudget`` /
  ``AllocTracker`` snapshots (waiter count + longest-wait age), live
  ``PipelineStats`` lane samples, and the merged live registry tree.
  Triggers: explicit API, ``TPQ_DUMP_SIGNAL`` (``faulthandler``-style,
  opt-in), an unhandled exception in a pipeline/loader worker, and
  exit-on-unhandled-exception (both file triggers gated on ``TPQ_FLIGHT``).

- :class:`Watchdog` — a daemon thread (same lifecycle discipline as
  :class:`Sampler`) watching per-stage progress heartbeats; when no
  watched counter advances within ``TPQ_HANG_S`` / ``hang_s=`` it writes a
  flight dump and either logs-and-continues or aborts the in-flight
  budget so the submitter raises :class:`~tpu_parquet.errors.HangError`.

- :func:`autopsy_dump` — the ``pq_tool autopsy`` backend: classifies each
  dumped thread's stack (budget-wait / queue-get / future-wait /
  device-sync / lock-wait), names the lane that stopped advancing first,
  and renders a one-line probable cause.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
import weakref
from collections import deque
from typing import Optional

__all__ = [
    "FLIGHT_VERSION", "OBS_VERSION", "ConsumerLane", "FlightRecorder",
    "LatencyHistogram", "MetricsDumper", "RequestTrace",
    "Sampler", "StatsRegistry", "TailSampler", "Tracer", "Watchdog",
    "autopsy_dump",
    "current_request_trace", "current_tracer", "doctor_registry",
    "env_float", "env_int", "fleet_host",
    "flight_dump_path",
    "flight_recorder", "install_flight_hooks", "note_worker_crash",
    "register_flight_registry", "register_flight_source",
    "render_openmetrics",
    "resolve_hang_s", "resolve_sample_ms", "resolve_tracer",
    "set_request_trace", "trace_summary", "warn_env_once",
]

# version of every schema this module emits (the registry tree, the trace
# file's otherData, the histogram dict) — bench parsers and the driver key
# on it, and the golden-key tests in tests/test_obs.py pin the key sets
OBS_VERSION = 1


# ---------------------------------------------------------------------------
# env knob parsing: malformed values degrade, never raise
# ---------------------------------------------------------------------------

# (name, raw) pairs already warned about — one line per bad value, not one
# per reader construction (a scan_files over 1000 shards must not log 1000x)
_env_warned: "set[tuple[str, str]]" = set()


def _env_num(name: str, default, cast, lo=None, hi=None):
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        v = cast(raw)
    except (TypeError, ValueError):
        key = (name, raw)
        if key not in _env_warned:
            _env_warned.add(key)
            import logging

            logging.getLogger(__name__).warning(
                "%s=%r is not a valid %s; using the default %r",
                name, raw, cast.__name__, default)
        return default
    if lo is not None and v < lo:
        v = lo
    if hi is not None and v > hi:
        v = hi
    return v


def env_float(name: str, default: float, lo=None, hi=None) -> float:
    """``float(os.environ[name])`` with the TPQ_HANG_POLICY degradation
    contract: unset → default; malformed → default plus ONE warning line
    (an env typo must never turn every reader construction into a raise);
    out-of-range values clamp to ``[lo, hi]``."""
    return _env_num(name, default, float, lo, hi)


def env_int(name: str, default: int, lo=None, hi=None) -> int:
    """Integer twin of :func:`env_float`, same degradation contract."""
    return _env_num(name, default, int, lo, hi)


def warn_env_once(name: str, raw: str, fallback) -> None:
    """One warning line per bad (env var, value) pair — the shared
    degradation mechanism for non-numeric knobs (TPQ_ON_DATA_ERROR,
    TPQ_VALIDATE, TPQ_DATA_ERROR_BUDGET) so a typo never raises and never
    floods the log (same `_env_warned` set as the numeric knobs)."""
    key = (name, raw)
    if key not in _env_warned:
        _env_warned.add(key)
        import logging

        logging.getLogger(__name__).warning(
            "%s=%r is not valid; using %r", name, raw, fallback)


# ---------------------------------------------------------------------------
# latency histograms
# ---------------------------------------------------------------------------

class LatencyHistogram:
    """Log2-bucketed latency distribution; lock-protected, mergeable.

    Bucket ``i`` holds durations whose nanosecond count has bit length ``i``
    (i.e. ``[2^(i-1), 2^i)`` ns; bucket 0 is exactly 0 ns) — ~62 sparse
    buckets cover 1 ns to minutes with <2x relative error, which is what a
    p50/p95 over decode stages needs.  Quantiles interpolate at the bucket's
    geometric midpoint.  ``merge_from`` folds another histogram in
    (thread-safe on both sides); ``as_dict``/``from_dict`` round-trip across
    process boundaries (the loader-resume shaped 2-process test).
    """

    __slots__ = ("_lock", "buckets", "count", "sum_seconds", "max_seconds",
                 "exemplars")

    def __init__(self):
        self._lock = threading.Lock()
        self.buckets: dict[int, int] = {}
        self.count = 0
        self.sum_seconds = 0.0
        self.max_seconds = 0.0
        # bucket idx -> [trace_id, seconds]: the most recent RETAINED trace
        # whose duration landed in that bucket — the OpenMetrics exemplar
        # (one per bucket, last-writer-wins; a map, not a ring, so the
        # memory bound is the bucket count)
        self.exemplars: "dict[int, list]" = {}

    @staticmethod
    def bucket_index(seconds: float) -> int:
        """The bucket a duration lands in (the ``record`` formula)."""
        ns = int(seconds * 1e9)
        return ns.bit_length() if ns > 0 else 0

    @staticmethod
    def bucket_upper_seconds(idx: int) -> float:
        """Bucket ``idx``'s exclusive upper bound in seconds (``2^idx`` ns;
        bucket 0 is exactly 0) — the OpenMetrics ``le`` value."""
        return 0.0 if idx <= 0 else (2.0 ** idx) / 1e9

    def record(self, seconds: float, exemplar: "str | None" = None) -> None:
        idx = self.bucket_index(seconds)
        with self._lock:
            self.buckets[idx] = self.buckets.get(idx, 0) + 1
            self.count += 1
            self.sum_seconds += seconds
            if seconds > self.max_seconds:
                self.max_seconds = seconds
            if exemplar is not None:
                # raw seconds, never rounded: the exemplar's value must
                # re-derive the SAME bucket index (fuzz target #23 checks)
                self.exemplars[idx] = [str(exemplar), seconds]

    def merge_from(self, other: "LatencyHistogram") -> None:
        with other._lock:
            snap = (dict(other.buckets), other.count, other.sum_seconds,
                    other.max_seconds, dict(other.exemplars))
        self._merge_snap(*snap)

    def _merge_snap(self, buckets, count, sum_s, max_s,
                    exemplars=None) -> None:
        with self._lock:
            for i, n in buckets.items():
                self.buckets[i] = self.buckets.get(i, 0) + n
            self.count += count
            self.sum_seconds += sum_s
            self.max_seconds = max(self.max_seconds, max_s)
            for i, ex in (exemplars or {}).items():
                self.exemplars[i] = list(ex)

    def quantile(self, q: float) -> float:
        """Approximate quantile in seconds (geometric bucket midpoint)."""
        with self._lock:
            if not self.count:
                return 0.0
            target = q * self.count
            seen = 0
            for i in sorted(self.buckets):
                seen += self.buckets[i]
                if seen >= target:
                    if i == 0:
                        return 0.0
                    # bucket spans [2^(i-1), 2^i) ns: geometric midpoint
                    return (2.0 ** (i - 0.5)) / 1e9
            return self.max_seconds

    @property
    def mean_seconds(self) -> float:
        return self.sum_seconds / self.count if self.count else 0.0

    def as_dict(self) -> dict:
        with self._lock:
            buckets = {str(i): n for i, n in sorted(self.buckets.items())}
            count, sum_s, max_s = self.count, self.sum_seconds, self.max_seconds
            exemplars = {str(i): [ex[0], ex[1]]
                         for i, ex in sorted(self.exemplars.items())}
        out = {
            "count": count,
            "sum_seconds": round(sum_s, 6),
            "max_seconds": round(max_s, 6),
            "p50_seconds": round(self.quantile(0.50), 9),
            "p95_seconds": round(self.quantile(0.95), 9),
            "buckets": buckets,
        }
        if exemplars:
            # only when present: exemplar-free histograms keep the exact
            # key set the golden tests pin, and the round-trip below holds
            out["exemplars"] = exemplars
        return out

    @staticmethod
    def _parse_exemplars(d: dict) -> dict:
        return {int(i): [str(ex[0]), float(ex[1])]
                for i, ex in (d.get("exemplars") or {}).items()
                if isinstance(ex, (list, tuple)) and len(ex) == 2}

    @classmethod
    def from_dict(cls, d: dict) -> "LatencyHistogram":
        h = cls()
        h._merge_snap({int(i): int(n) for i, n in d.get("buckets", {}).items()},
                      int(d.get("count", 0)), float(d.get("sum_seconds", 0.0)),
                      float(d.get("max_seconds", 0.0)),
                      cls._parse_exemplars(d))
        return h

    def merge_dict(self, d: dict) -> None:
        """Fold a serialized histogram (another process's) into this one."""
        self._merge_snap(
            {int(i): int(n) for i, n in d.get("buckets", {}).items()},
            int(d.get("count", 0)), float(d.get("sum_seconds", 0.0)),
            float(d.get("max_seconds", 0.0)), self._parse_exemplars(d))


# ---------------------------------------------------------------------------
# request tracing: per-request span trees + tail sampling
# ---------------------------------------------------------------------------

# version of the retained-trace document (`RequestTrace.as_dict`,
# `TailSampler.dump`) — `pq_tool trace --request` keys on it
TRACE_VERSION = 1

# process-unique trace-id minting: a random base per process plus a
# counter, so ids stay unique across services in one process and collide
# across processes only with 2^-32 probability
_trace_lock = threading.Lock()
_trace_base = os.urandom(4).hex()
_trace_seq = 0


def _mint_trace_id() -> str:
    global _trace_seq
    with _trace_lock:
        _trace_seq += 1
        return f"{_trace_base}-{_trace_seq:06x}"


_fleet_host_cache: "str | None" = None


def fleet_host() -> str:
    """This process's host name as it appears in spool snapshots and
    stitched traces (cached; never raises)."""
    global _fleet_host_cache
    if _fleet_host_cache is None:
        try:
            _fleet_host_cache = os.uname().nodename or "localhost"
        except (AttributeError, OSError):
            _fleet_host_cache = "localhost"
    return _fleet_host_cache


class _TraceSpan:
    """Span context manager for :class:`RequestTrace` (slots, one lock
    round-trip per open and per close)."""

    __slots__ = ("_tr", "_idx")

    def __init__(self, tr, idx):
        self._tr = tr
        self._idx = idx

    def __enter__(self):
        return self

    def __exit__(self, tp, val, tb):
        self._tr._close(self._idx, val)
        return False


class RequestTrace:
    """One request's span tree: allocation-light, always on, completed for
    EVERY request so the tail sampler can decide afterwards (Dapper-style
    tail sampling needs the whole tree in hand at the decision point).

    Spans are small lists ``[name, t0_rel, dur, parent, args]`` appended at
    OPEN time, so a parent's index is always smaller than its children's
    (the well-nestedness invariant fuzz target #23 checks).  Nesting is
    per-thread: each thread keeps its own open-span stack, and the first
    span a helper thread opens parents to the top-level (the producer /
    prefetch-worker / fetch-engine spans hang off the request root without
    cross-thread stack corruption).  A ``max_spans`` cap bounds memory per
    request (``TPQ_TRACE_SPANS``); drops are counted, never silent.
    """

    __slots__ = ("trace_id", "t0", "t0_unix", "duration_s", "spans",
                 "max_spans", "dropped", "error", "flags", "origin",
                 "_lock", "_local")

    def __init__(self, trace_id: "str | None" = None,
                 max_spans: "int | None" = None):
        if max_spans is None:
            max_spans = env_int("TPQ_TRACE_SPANS", 512, lo=1)
        self.trace_id = trace_id or _mint_trace_id()
        self.origin: "dict | None" = None
        self.t0 = time.perf_counter()
        self.t0_unix = time.time()
        self.duration_s: "float | None" = None
        self.spans: list = []  # [name, t0_rel, dur, parent, args]
        self.max_spans = int(max_spans)
        self.dropped = 0
        self.error: "dict | None" = None
        self.flags: set = set()
        self._lock = threading.Lock()
        self._local = threading.local()

    # -- recording ------------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def span(self, name: str, **args):
        """Open a nested span (context manager).  Over the cap: counted
        drop, shared no-op."""
        st = self._stack()
        parent = st[-1] if st else -1
        with self._lock:
            if len(self.spans) >= self.max_spans:
                self.dropped += 1
                return _NULL_SPAN
            idx = len(self.spans)
            self.spans.append([name, time.perf_counter() - self.t0, None,
                               parent, args or None])
        st.append(idx)
        return _TraceSpan(self, idx)

    def _close(self, idx: int, exc) -> None:
        now = time.perf_counter() - self.t0
        st = self._stack()
        # pop through idx: an interleaved close (fuzzed op streams) closes
        # the children it skipped, keeping every retained tree well-nested
        while st and st[-1] >= idx:
            st.pop()
        with self._lock:
            s = self.spans[idx]
            if s[2] is None:
                s[2] = max(now - s[1], 0.0)
            if exc is not None:
                args = s[4] or {}
                args["error"] = type(exc).__name__
                s[4] = args

    def add_timed(self, name: str, t0: float, t1: float, **args) -> None:
        """Record an already-timed interval (perf_counter seconds) as a
        closed child of the current thread's open span."""
        st = self._stack()
        parent = st[-1] if st else -1
        with self._lock:
            if len(self.spans) >= self.max_spans:
                self.dropped += 1
                return
            self.spans.append([name, t0 - self.t0, max(t1 - t0, 0.0),
                               parent, args or None])

    def annotate(self, idx: "int | None" = None, **kv) -> None:
        """Attach facts to a span (default: the current thread's open
        one) — retry counts, hedge outcomes, byte sizes."""
        st = self._stack()
        if idx is None:
            idx = st[-1] if st else None
        if idx is None:
            return
        with self._lock:
            if 0 <= idx < len(self.spans):
                s = self.spans[idx]
                args = s[4] or {}
                args.update(kv)
                s[4] = args

    def mark_error(self, exc: BaseException) -> None:
        with self._lock:
            self.error = {"type": type(exc).__name__,
                          "message": str(exc)[:300]}

    def set_flag(self, flag: str) -> None:
        """Request-level outcome flags the sampler keys on
        (``deadline``, ``shed``, ``cancelled``)."""
        with self._lock:
            self.flags.add(str(flag))

    def finish(self) -> float:
        """Close the tree (idempotent); returns the request duration."""
        with self._lock:
            if self.duration_s is None:
                self.duration_s = time.perf_counter() - self.t0
            # close any span left open by an abandoned thread: a retained
            # tree never carries null durations
            for s in self.spans:
                if s[2] is None:
                    s[2] = max((self.t0 + self.duration_s)
                               - (self.t0 + s[1]), 0.0)
            return self.duration_s

    # -- cross-process stitching ----------------------------------------------

    def trace_context(self) -> dict:
        """Exportable context blob identifying this request across process
        seams — hand it (e.g. JSON via ``TPQ_TRACE_CONTEXT``) to a child
        process whose traces should re-parent under this request."""
        return {
            "trace_version": TRACE_VERSION,
            "trace_id": self.trace_id,
            "host": fleet_host(),
            "pid": os.getpid(),
            "t0_unix": round(self.t0_unix, 3),
        }

    @classmethod
    def adopt_context(cls, ctx: dict,
                      max_spans: "int | None" = None) -> "RequestTrace":
        """Create a child-process trace re-parented under the originating
        request described by ``ctx`` (a :meth:`trace_context` blob).  The
        child gets its OWN trace id (ids stay process-unique); ``origin``
        records the parent so the aggregated view can stitch the trees.
        Raises ``ValueError`` on a malformed blob — callers adopting from
        an env var degrade via ``warn_env_once`` instead."""
        if not isinstance(ctx, dict):
            raise ValueError(f"trace context must be a dict, got "
                             f"{type(ctx).__name__}")
        tid = ctx.get("trace_id")
        if not isinstance(tid, str) or not tid:
            raise ValueError(f"trace context missing trace_id: {ctx!r}")
        tr = cls(max_spans=max_spans)
        tr.origin = {"trace_id": tid,
                     "host": str(ctx.get("host") or "unknown"),
                     "pid": int(ctx.get("pid") or 0)}
        return tr

    # -- export ---------------------------------------------------------------

    def as_dict(self) -> dict:
        with self._lock:
            spans = [{
                "name": s[0],
                "t_s": round(s[1], 6),
                "dur_s": round(s[2], 6) if s[2] is not None else None,
                "parent": s[3],
                **({"args": s[4]} if s[4] else {}),
            } for s in self.spans]
            doc = {
                "trace_version": TRACE_VERSION,
                "trace_id": self.trace_id,
                "host": fleet_host(),
                "pid": os.getpid(),
                "t0_unix": round(self.t0_unix, 3),
                "duration_s": (round(self.duration_s, 6)
                               if self.duration_s is not None else None),
                "error": self.error,
                "flags": sorted(self.flags),
                "dropped": self.dropped,
                "spans": spans,
            }
            if self.origin:
                doc["origin"] = dict(self.origin)
            return doc


# the request trace of the thread currently executing a request — how code
# with no token in hand (plan/result cache probes, device dispatch) finds
# the trace; serve workers and stream producers set/restore it per unit
_req_local = threading.local()


def current_request_trace() -> "RequestTrace | None":
    return getattr(_req_local, "trace", None)


def set_request_trace(trace: "RequestTrace | None"):
    """Install ``trace`` as this thread's current request trace; returns
    the previous one (callers restore it, nesting-safe)."""
    prev = getattr(_req_local, "trace", None)
    _req_local.trace = trace
    return prev


class TailSampler:
    """Tail sampler + bounded retained-trace ring.

    Every request's completed tree is ``offer()``-ed with its outcome; the
    sampler RETAINS the interesting ones — errored, deadline-exceeded,
    brownout-shed, slower than a rolling quantile of its own traffic
    (``TPQ_TRACE_SLOW_Q`` over an internal :class:`LatencyHistogram`), or
    1-in-N (``TPQ_TRACE_TAIL``; 1 retains everything, 0 disables request
    tracing entirely) — serialized into a ring bounded by BYTES
    (``TPQ_TRACE_RING``), evicting oldest-first.  ``offer`` returns whether
    the trace was retained, so exemplars only ever name a trace that can
    actually be fetched back (``get``/``dump`` → ``pq_tool trace
    --request``).
    """

    # the rolling-quantile gate needs this many samples before "slow" means
    # anything; below it only errors/flags/1-in-N retain
    SLOW_MIN_SAMPLES = 32

    def __init__(self, one_in_n: "int | None" = None,
                 ring_bytes: "int | None" = None,
                 slow_q: "float | None" = None):
        if one_in_n is None:
            one_in_n = env_int("TPQ_TRACE_TAIL", 128, lo=0)
        if ring_bytes is None:
            ring_bytes = env_int("TPQ_TRACE_RING", 1 << 20, lo=4096)
        if slow_q is None:
            slow_q = env_float("TPQ_TRACE_SLOW_Q", 0.95, lo=0.5, hi=0.9999)
        self.one_in_n = int(one_in_n)
        self.ring_bytes = int(ring_bytes)
        self.slow_q = float(slow_q)
        self._lock = threading.Lock()
        self._ring: "deque[tuple[str, bytes]]" = deque()
        self._index: dict[str, bytes] = {}
        self._hist = LatencyHistogram()
        self.offered = 0
        self.retained = 0
        self.evicted = 0
        self.retained_bytes = 0

    @property
    def enabled(self) -> bool:
        return self.one_in_n > 0

    def offer(self, trace: RequestTrace, duration_s: "float | None" = None,
              error: bool = False) -> bool:
        """Decide on a completed trace; retain interesting ones.  Returns
        True iff retained (the exemplar gate)."""
        if not self.enabled:
            return False
        dur = trace.finish() if duration_s is None else float(duration_s)
        slow_bar = None
        if self._hist.count >= self.SLOW_MIN_SAMPLES:
            slow_bar = self._hist.quantile(self.slow_q)
        self._hist.record(dur)
        with self._lock:
            self.offered += 1
            keep = (error or trace.error is not None or bool(trace.flags)
                    or (slow_bar is not None and dur >= slow_bar)
                    or self.offered % self.one_in_n == 0)
        if not keep:
            return False
        blob = json.dumps(trace.as_dict(), default=repr).encode()
        with self._lock:
            if len(blob) > self.ring_bytes:
                return False  # one pathological tree must not flush the ring
            self._ring.append((trace.trace_id, blob))
            self._index[trace.trace_id] = blob
            self.retained += 1
            self.retained_bytes += len(blob)
            while self.retained_bytes > self.ring_bytes and len(self._ring) > 1:
                old_id, old = self._ring.popleft()
                self.retained_bytes -= len(old)
                self.evicted += 1
                if self._index.get(old_id) is old:
                    del self._index[old_id]
        return True

    def get(self, trace_id: str) -> "dict | None":
        with self._lock:
            blob = self._index.get(trace_id)
        return json.loads(blob) if blob is not None else None

    def traces(self) -> "list[dict]":
        with self._lock:
            blobs = [b for _, b in self._ring]
        return [json.loads(b) for b in blobs]

    def counters(self) -> dict:
        with self._lock:
            return {
                "offered": self.offered,
                "retained": self.retained,
                "evicted": self.evicted,
                "retained_bytes": self.retained_bytes,
                "ring_capacity_bytes": self.ring_bytes,
            }

    def dump(self, path: str) -> str:
        """Write the retained traces (versioned; the ``pq_tool trace
        --request`` input).  Same mkdir-parents contract as Tracer.write."""
        doc = {"trace_dump_version": TRACE_VERSION, "traces": self.traces()}
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.write("\n")
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------------
# flight recorder: the always-on black box
# ---------------------------------------------------------------------------

# version of the dump snapshot schema `FlightRecorder.dump` writes and
# `autopsy_dump` consumes — golden-key-tested like the registry tree
FLIGHT_VERSION = 1

# live providers the dump pulls from (module-level, not per-recorder, so a
# test's private recorder still sees the process's live pipelines/readers):
# weakrefs only — registration must never extend a reader's lifetime
_flight_lock = threading.Lock()
_flight_sources: "list[tuple[str, weakref.ref, str]]" = []
_flight_registries: "list[tuple[weakref.ref, str]]" = []


def _prune_providers(lst) -> None:
    lst[:] = [entry for entry in lst if entry[-2]() is not None]


def register_flight_source(label: str, obj, method: str = "sample") -> None:
    """Register a live counter source (``obj.method() -> {name: number}``)
    for flight dumps — e.g. every :class:`~tpu_parquet.pipeline
    .PipelineStats` registers its ``sample`` so a dump shows the per-lane
    seconds and queue depth at the moment of the wedge.  Weakly held."""
    with _flight_lock:
        _prune_providers(_flight_sources)
        _flight_sources.append((label, weakref.ref(obj), method))


def register_flight_registry(obj, method: str = "obs_registry") -> None:
    """Register a live registry provider (``obj.method() ->
    StatsRegistry``): readers and loaders register themselves so a dump
    embeds the same tree a clean close would have written.  Weakly held."""
    with _flight_lock:
        _prune_providers(_flight_registries)
        _flight_registries.append((weakref.ref(obj), method))


def flight_dump_path() -> str:
    """Where unsolicited dumps land: ``TPQ_FLIGHT`` when set, else
    ``tpq_flight.<pid>.json`` in the working directory."""
    return os.environ.get("TPQ_FLIGHT") or f"tpq_flight.{os.getpid()}.json"


class FlightRecorder:
    """Always-on bounded in-memory ring of recent trace events.

    One ``deque(maxlen=capacity)`` per thread (``TPQ_RING_EVENTS`` events
    each, default 256; 0 disables), appended lock-free on the hot path (a
    thread only ever appends to its own ring; CPython deque appends are
    atomic) — the recording cost is one thread-local attribute read, a
    tuple build, and an append, guarded <3% by the tier-1 overhead test.
    A chatty thread can never evict a stalled thread's history, which is
    exactly the history a hang autopsy needs.

    ``snapshot()``/``dump()`` produce the versioned post-mortem document:
    ring events per thread with ages, every thread's current stack, live
    budget/tracker/pipeline state, and the merged live registry tree.
    Dumping never raises into the caller's control flow beyond I/O errors
    on the explicit path — every provider is individually guarded.
    """

    def __init__(self, capacity: "int | None" = None):
        if capacity is None:
            capacity = env_int("TPQ_RING_EVENTS", 256, lo=0)
        self.capacity = max(int(capacity), 0)
        self._lock = threading.Lock()
        self._threads: "dict[int, tuple[str, deque]]" = {}
        self._local = threading.local()

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    # -- recording (hot path) -------------------------------------------------

    def _register_thread(self) -> deque:
        t = threading.current_thread()
        ring: deque = deque(maxlen=self.capacity)
        with self._lock:
            # keyed by ident: reused idents overwrite their dead
            # predecessor, so the map stays bounded by live threads
            self._threads[t.ident or 0] = (t.name, ring)
        self._local.ring = ring
        return ring

    def record(self, ph: str, name: str, ts: float, dur: float = 0.0,
               args: "dict | None" = None) -> None:
        """Append one event (``ts``/``dur`` in perf_counter seconds)."""
        if not self.capacity:
            return
        try:
            ring = self._local.ring
        except AttributeError:
            ring = self._register_thread()
        ring.append((ph, name, ts, dur, args))

    # -- snapshot / dump ------------------------------------------------------

    @staticmethod
    def _format_stack(frame) -> "list[dict]":
        import traceback

        out = []
        for fs in traceback.extract_stack(frame):
            out.append({"file": fs.filename, "line": fs.lineno,
                        "func": fs.name, "code": fs.line or ""})
        return out  # outermost first, same order as a printed traceback

    def snapshot(self, reason: str = "explicit",
                 watchdog: "dict | None" = None,
                 error: "BaseException | None" = None) -> dict:
        now_p = time.perf_counter()
        with self._lock:
            rings = list(self._threads.items())
        threads = {}
        for tid, (name, ring) in rings:
            # writers append to their own ring lock-free (by design), and a
            # CPython deque raises if mutated during iteration even at
            # constant maxlen size — retry the copy; a busy thread's ring
            # settles between appends, and a dump must never be lost to it
            events: list = []
            for _ in range(5):
                try:
                    events = list(ring)
                    break
                except RuntimeError:
                    continue
            threads[tid] = (name, events)
        frames = sys._current_frames()
        # `or 0`: enumerate() can briefly surface a thread whose ident is
        # not yet assigned (mid-start) — it must not break the dump
        alive = {(t.ident or 0): t.name for t in threading.enumerate()}
        tout: dict = {}
        for tid in sorted(set(threads) | set(frames) | set(alive)):
            name, ring = threads.get(tid, (alive.get(tid, "?"), []))
            events = [{
                "ph": ph, "name": nm,
                "age_s": round(now_p - ts, 6),
                "dur_s": round(dur, 6),
                **({"args": a} if a else {}),
            } for ph, nm, ts, dur, a in ring]
            entry: dict = {
                "name": alive.get(tid, name),
                "alive": tid in alive,
                "events": events,
                "last_event": events[-1] if events else None,
            }
            f = frames.get(tid)
            if f is not None:
                try:
                    entry["stack"] = self._format_stack(f)
                except Exception:  # noqa: BLE001 — a dump must not fail
                    entry["stack"] = []
            tout[str(tid)] = entry
        doc = {
            "flight_version": FLIGHT_VERSION,
            "obs_version": OBS_VERSION,
            "reason": reason,
            "ts": round(time.time(), 3),
            "pid": os.getpid(),
            "ring_capacity": self.capacity,
            "threads": tout,
            "watchdog": watchdog,
            "error": ({"type": type(error).__name__,
                       "message": str(error)[:500]}
                      if error is not None else None),
        }
        try:
            from . import alloc

            doc["budgets"] = alloc.budget_snapshots()
            doc["trackers"] = alloc.tracker_snapshots()
        except Exception:  # noqa: BLE001
            doc["budgets"], doc["trackers"] = [], []
        samples: dict = {}
        with _flight_lock:
            sources = list(_flight_sources)
            registries = list(_flight_registries)
        for label, ref, method in sources:
            obj = ref()
            if obj is None:
                continue
            try:
                v = getattr(obj, method)()
            except Exception:  # noqa: BLE001 — a dead source never kills a dump
                continue
            if isinstance(v, dict):
                samples[label] = v
        doc["samples"] = samples
        reg_tree = None
        merged = StatsRegistry()
        found = False
        for ref, method in registries:
            obj = ref()
            if obj is None:
                continue
            try:
                merged.merge_from(getattr(obj, method)())
                found = True
            except Exception:  # noqa: BLE001
                continue
        if found:
            try:
                reg_tree = merged.as_dict()
            except Exception:  # noqa: BLE001
                reg_tree = None
        doc["registry"] = reg_tree
        return doc

    def dump(self, path: "str | None" = None, reason: str = "explicit",
             watchdog: "dict | None" = None,
             error: "BaseException | None" = None) -> str:
        """Write a snapshot to ``path`` (default :func:`flight_dump_path`);
        returns the path.  Same mkdir-parents contract as Tracer.write."""
        doc = self.snapshot(reason=reason, watchdog=watchdog, error=error)
        path = path or flight_dump_path()
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f, default=repr)
            f.write("\n")
        return path


_flight: "FlightRecorder | None" = None


def flight_recorder() -> FlightRecorder:
    """The process-wide flight recorder (capacity from ``TPQ_RING_EVENTS``
    at first use).  Always returns an object; with capacity 0 its record
    calls are no-ops and tracers skip the tee entirely."""
    global _flight
    if _flight is None:
        with _flight_lock:
            if _flight is None:
                _flight = FlightRecorder()
    return _flight


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------

class _NullSpan:
    """The shared no-op context manager a disabled tracer hands out."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tr", "_name", "_args", "_t0")

    def __init__(self, tr, name, args):
        self._tr = tr
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tr.complete(self._name, self._t0, time.perf_counter(),
                          **self._args)
        return False


class Tracer:
    """Thread-safe span tracer with Chrome trace-event JSON export.

    Spans are recorded as complete events (``ph: "X"``: one event carrying
    ``ts`` + ``dur`` in microseconds on the shared ``perf_counter`` clock),
    so nesting is implied by containment — Perfetto and ``chrome://tracing``
    rebuild the flame graph per (pid, tid) without begin/end pairing.
    ``instant``/``counter`` events carry point-in-time facts (a chunk's
    chosen ship route, the shuffle window's occupancy).

    When ``enabled`` is False every record call is one ``if`` and ``span()``
    returns a module-level no-op singleton — the hot loops keep their obs
    calls unconditionally and pay <3% (tier-1 guarded).

    Every tracer additionally TEES its events into a
    :class:`FlightRecorder` ring (the process recorder by default, even
    when disabled — that is the black box: the last N events per thread
    survive in memory with no ``TPQ_TRACE`` set).  ``ring=None`` opts a
    tracer out entirely; hot-path guards check :attr:`active` (enabled OR
    ring-teed) so span timing happens exactly when someone is listening.
    """

    def __init__(self, path: "str | None" = None, enabled: bool = True,
                 ring: "FlightRecorder | None | type[Ellipsis]" = ...):
        self.enabled = bool(enabled)
        self.path = path
        if ring is ...:
            ring = flight_recorder()
        self.ring = ring if (ring is not None and ring.enabled) else None
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._named_tids: set[int] = set()
        self._written = False
        if path is not None and self.enabled:
            atexit.register(self._atexit_write)

    @property
    def active(self) -> bool:
        """True when recording anywhere (the event list or the flight
        ring) — the guard hot loops use around building span args."""
        return self.enabled or self.ring is not None

    # -- recording ------------------------------------------------------------

    def _tid(self) -> int:
        t = threading.current_thread()
        tid = t.ident or 0
        if tid not in self._named_tids:
            with self._lock:
                if tid not in self._named_tids:  # re-check under the lock
                    self._named_tids.add(tid)
                    self._events.append({
                        "name": "thread_name", "ph": "M",
                        "pid": self._pid, "tid": tid,
                        "args": {"name": t.name},
                    })
        return tid

    def span(self, name: str, **args):
        """Context manager timing a nested span (no-op when neither the
        event list nor the flight ring is recording)."""
        if not self.enabled and self.ring is None:
            return _NULL_SPAN
        return _Span(self, name, args)

    def complete(self, name: str, t0: float, t1: float, **args) -> None:
        """Record an already-timed interval (perf_counter seconds)."""
        ring = self.ring
        if ring is not None:
            ring.record("X", name, t0, t1 - t0, args or None)
        if not self.enabled:
            return
        ev = {
            "name": name, "ph": "X", "ts": int(t0 * 1e6),
            "dur": max(int((t1 - t0) * 1e6), 0),
            "pid": self._pid, "tid": self._tid(),
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def instant(self, name: str, **args) -> None:
        ring = self.ring
        if ring is not None:
            ring.record("i", name, time.perf_counter(), 0.0, args or None)
        if not self.enabled:
            return
        ev = {
            "name": name, "ph": "i", "s": "t",
            "ts": int(time.perf_counter() * 1e6),
            "pid": self._pid, "tid": self._tid(),
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def counter(self, name: str, track_id=None, **values) -> None:
        """One counter sample.  ``track_id`` sets the trace event's ``id``
        field: Chrome counter tracks are keyed ``(pid, name[, id])``, so
        same-named counters from different emitters (two readers of one
        ``scan_files`` sampling onto the shared tracer) render as separate
        ``name[id]`` tracks instead of interleaving into one sawtooth."""
        ring = self.ring
        if ring is not None:
            ring.record("C", name, time.perf_counter(), 0.0, values or None)
        if not self.enabled:
            return
        ev = {
            "name": name, "ph": "C",
            "ts": int(time.perf_counter() * 1e6),
            "pid": self._pid, "tid": self._tid(),
            "args": values,
        }
        if track_id is not None:
            ev["id"] = str(track_id)
        with self._lock:
            self._events.append(ev)

    # -- merge / export -------------------------------------------------------

    def merge_events(self, events: list) -> None:
        """Fold exported events (typically another process's) in verbatim —
        pids differ, so Perfetto renders them as separate process tracks."""
        with self._lock:
            self._events.extend(events)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def export(self, registry: "StatsRegistry | None" = None) -> dict:
        """The Chrome trace-event *object form*: events plus ``otherData``
        (obs version, and the registry tree when given — so one artifact
        carries both the timeline and the aggregate metrics)."""
        other: dict = {"obs_version": OBS_VERSION}
        if registry is not None:
            other["registry"] = registry.as_dict()
        return {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "otherData": other,
        }

    def write(self, path: "str | None" = None,
              registry: "StatsRegistry | None" = None) -> "str | None":
        """Serialize to ``path`` (default: the construction path).  Missing
        parent directories are created here, not discovered at close time:
        ``TPQ_TRACE=runs/today/t.json`` into a fresh tree must not fail with
        a late FileNotFoundError after the run already happened."""
        path = path or self.path
        if path is None:
            return None
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.export(registry), f)
            f.write("\n")
        self._written = True
        return path

    def _atexit_write(self) -> None:
        if self._written or not self._events:
            return
        try:
            self.write()
        except OSError:
            pass  # interpreter teardown: a dead path must not mask the exit


_DISABLED = Tracer(enabled=False)
_global: "Tracer | None" = None
_global_key: "str | None" = None
_global_lock = threading.Lock()


def current_tracer() -> Tracer:
    """The process-wide tracer: enabled iff ``TPQ_TRACE=<path>`` is set
    (rebuilt when the env changes, so monkeypatched tests see theirs); the
    shared disabled singleton otherwise."""
    global _global, _global_key
    key = os.environ.get("TPQ_TRACE", "")
    if not key:
        return _DISABLED if _global_key in (None, "") else _refresh("")
    with _global_lock:
        if _global is None or _global_key != key:
            _global = Tracer(path=key)
            _global_key = key
        return _global


def _refresh(key: str) -> Tracer:
    global _global, _global_key
    with _global_lock:
        _global, _global_key = None, key
    return _DISABLED


def resolve_tracer(trace) -> "tuple[Tracer, bool]":
    """Resolve a ``trace=`` kwarg to ``(tracer, owned)``.

    ``None`` → the process tracer (owned by the process, not the caller);
    a path → a fresh enabled tracer the CALLER must ``write()`` (readers do
    so in ``close()``); a :class:`Tracer` → itself, not owned.
    """
    if trace is None:
        return current_tracer(), False
    if isinstance(trace, Tracer):
        return trace, False
    return Tracer(path=os.fspath(trace)), True


# ---------------------------------------------------------------------------
# counter sampler
# ---------------------------------------------------------------------------

def resolve_sample_ms(sample_ms=None) -> float:
    """Resolve a ``sample_ms=`` kwarg against ``TPQ_SAMPLE_MS`` (kwarg wins;
    0 or unset disables sampling)."""
    if sample_ms is not None:
        try:
            return max(float(sample_ms), 0.0)
        except (TypeError, ValueError):
            return 0.0
    return env_float("TPQ_SAMPLE_MS", 0.0, lo=0.0)


class Sampler:
    """Daemon thread snapshotting counter sources into a tracer every N ms.

    The tracer's spans say how long each unit of work took; this says what
    the whole machine looked like OVER TIME — Chrome counter tracks of the
    cumulative stage seconds (their slope is live per-lane throughput), the
    prefetch queue depth, and the alloc watermarks, so Perfetto shows
    throughput/backpressure *curves* instead of end totals and a stall is
    visible as the flat stretch where every curve stops climbing.

    Sources are zero-arg callables returning ``{counter: number}``; each
    tick emits one ``tracer.counter(track, **values)`` per source.  A
    source that raises is skipped for that tick (``dropped`` counts them) —
    sampling must never take the run down.  Inert (``start`` is a no-op)
    when the tracer is disabled or the interval is 0, so callers wire it
    unconditionally.  Shutdown is thread-leak-safe: ``stop()`` joins the
    thread (which emits one final sample so the track's last point is the
    end state), and the thread is a daemon so an abandoned sampler can
    never hold the interpreter open.
    """

    def __init__(self, tracer: "Tracer | None", interval_ms: float,
                 name: str = "tpq-sampler", track_id=None):
        self.tracer = tracer
        self.interval_s = max(float(interval_ms or 0.0), 0.0) / 1e3
        self.name = name
        # forwarded as the counter events' Chrome track id so concurrent
        # samplers (scan_files opens several readers) keep separate tracks
        self.track_id = track_id
        self._sources: list = []  # [(track, fn)]
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: "threading.Thread | None" = None
        self.ticks = 0
        self.dropped = 0

    @property
    def enabled(self) -> bool:
        return (self.tracer is not None and self.tracer.enabled
                and self.interval_s > 0)

    def add_source(self, track: str, fn) -> "Sampler":
        with self._lock:
            self._sources.append((track, fn))
        return self

    def start(self) -> "Sampler":
        if not self.enabled or self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name=self.name,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Idempotent; joins the sampling thread (no leak, tier-1 guarded)."""
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=10.0)
        self._thread = None

    def __enter__(self) -> "Sampler":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _run(self) -> None:
        while True:
            self.sample_once()
            if self._stop.wait(self.interval_s):
                self.sample_once()  # final point: the track ends at the end state
                return

    def sample_once(self) -> None:
        with self._lock:
            sources = list(self._sources)
        for track, fn in sources:
            try:
                values = fn()
            except Exception:  # noqa: BLE001 — sampling never kills the run
                self.dropped += 1
                continue
            if not values:
                continue
            nums = {k: v for k, v in values.items()
                    if isinstance(v, (int, float)) and not isinstance(v, bool)}
            nums.pop("track_id", None)  # reserved for the keyword below
            if nums:
                # the EMIT side is guarded like the read side: scan_files
                # can close/write the shared tracer while a sibling
                # reader's sampler (or the watchdog) still ticks, and a
                # torn-down tracer must drop the tick, not kill the daemon
                # thread mid-run (satellite: shared-cadence hygiene)
                try:
                    self.tracer.counter(track, track_id=self.track_id, **nums)
                except Exception:  # noqa: BLE001
                    self.dropped += 1
        self.ticks += 1


# ---------------------------------------------------------------------------
# hang watchdog
# ---------------------------------------------------------------------------

def resolve_hang_s(hang_s=None) -> float:
    """Resolve a ``hang_s=`` kwarg against ``TPQ_HANG_S`` (kwarg wins —
    including an explicit 0, which disables the watchdog even when the env
    is set; unset/invalid env disables)."""
    if hang_s is not None:
        try:
            return max(float(hang_s), 0.0)
        except (TypeError, ValueError):
            return 0.0
    return env_float("TPQ_HANG_S", 0.0, lo=0.0)


class ConsumerLane:
    """A watchdog lane that distinguishes a wedged pipeline from a merely
    paused consumer.

    Every other heartbeat freezes the moment the consumer stops pulling
    (the prefetch window fills, counters stop) — so a training loop that
    pauses between batches to checkpoint or eval would look exactly like a
    hang.  This lane's value ADVANCES (wall clock) while the consumer is
    away (``idle``) and FREEZES at the moment it entered the producer
    (``producing``): the watchdog can then only fire while the consumer is
    genuinely blocked inside ``next()`` on a frozen pipeline.
    """

    __slots__ = ("_since",)

    def __init__(self):
        self._since: "float | None" = None

    def producing(self) -> None:
        """The consumer just entered the producer (blocked in next())."""
        self._since = time.monotonic()

    def idle(self) -> None:
        """About to yield: the consumer is going away with its batch."""
        self._since = None

    def value(self) -> float:
        s = self._since
        return s if s is not None else time.monotonic()


class Watchdog:
    """Daemon thread that detects a wedged pipeline from frozen heartbeats.

    ``watch(label, fn)`` registers a progress source: a zero-arg callable
    returning a number or a ``{lane: number}`` dict (each dict key becomes
    its own ``label.lane``).  The thread re-reads every source on a cadence
    of ``hang_s / 4`` (clamped to [20 ms, 1 s]); a lane "advances" when its
    value changes.  When **no** watched lane advances within ``hang_s``,
    the watchdog fires ONCE:

    1. writes a flight-recorder dump (``reason="hang"``) carrying per-lane
       no-advance ages and the lane that stopped advancing first, then
    2. policy ``"log"``: logs a warning and re-arms (graceful degradation:
       the run continues, the dump is the artifact), or policy ``"raise"``
       (the default, ``TPQ_HANG_POLICY`` overrides): builds a
       :class:`~tpu_parquet.errors.HangError` naming the dump and calls
       every registered abort hook — readers/loaders register their
       :meth:`~tpu_parquet.alloc.InFlightBudget.abort`, so the SUBMITTER
       blocked on backpressure wakes and raises instead of hanging
       forever.

    Lifecycle discipline matches :class:`Sampler`: inert (``start`` is a
    no-op) when ``hang_s`` is 0 or nothing is watched, ``stop()`` joins,
    the thread is a daemon, and every heartbeat/dump/hook call is guarded
    — a watchdog must never take a healthy run down.

    The deadline must exceed the longest legitimate single unit of work
    (one chunk's IO+decompress, one device sync): heartbeats are
    cumulative counters that only move when a unit COMPLETES.
    """

    def __init__(self, hang_s, recorder: "FlightRecorder | None" = None,
                 name: str = "tpq-watchdog", policy: "str | None" = None,
                 dump_path: "str | None" = None):
        self.hang_s = max(float(hang_s or 0.0), 0.0)
        self.recorder = recorder
        self.name = name
        env_policy = os.environ.get("TPQ_HANG_POLICY", "")
        self.policy = policy or env_policy or "raise"
        if self.policy not in ("raise", "log"):
            if policy:  # explicit kwarg: a code bug, fail loudly
                raise ValueError(
                    f"hang policy {self.policy!r} is not 'raise' or 'log'")
            # env typo: degrade to the safe default instead of failing
            # every reader/loader construction (resolve_hang_s treats a
            # malformed TPQ_HANG_S the same way — disabled, not fatal)
            import logging

            logging.getLogger(__name__).warning(
                "TPQ_HANG_POLICY=%r is not 'raise' or 'log'; using 'raise'",
                env_policy)
            self.policy = "raise"
        self.dump_path = dump_path
        self._watch: list = []  # [(label, fn)]
        self._abort_hooks: list = []
        self._last: dict = {}  # lane -> [value, t_change, advanced_ever]
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self.fired = False
        self.error = None
        self.last_dump: "str | None" = None
        self.dropped = 0

    @property
    def enabled(self) -> bool:
        return self.hang_s > 0

    def watch(self, label: str, fn) -> "Watchdog":
        with self._lock:
            self._watch.append((label, fn))
        return self

    def watch_consumer(self, label: str = "consumer") -> ConsumerLane:
        """Register (or REPLACE — one lane per label, so a reader's second
        scan doesn't leave a stale always-advancing lane that would defeat
        the all-frozen condition) a :class:`ConsumerLane` gate."""
        lane = ConsumerLane()
        with self._lock:
            self._watch = [(l, f) for l, f in self._watch if l != label]
            self._watch.append((label, lane.value))
            self._last.pop(label, None)
        return lane

    def add_abort_hook(self, fn) -> "Watchdog":
        """Register ``fn(exc)`` to run when the raise policy fires (e.g.
        ``budget.abort`` — the hook that turns a wedge into an error)."""
        with self._lock:
            self._abort_hooks.append(fn)
        return self

    def remove_abort_hook(self, fn) -> None:
        """Deregister a hook (idempotent).  A reader-lifetime watchdog sees
        one budget per scan: each feed must remove its hook on teardown or
        dead budgets accumulate for the reader's whole life."""
        with self._lock:
            try:
                self._abort_hooks.remove(fn)
            except ValueError:
                pass

    def check(self) -> None:
        """Submitter-side hook: raise the pending HangError, if any."""
        if self.error is not None:
            raise self.error

    def start(self) -> "Watchdog":
        if not self.enabled or not self._watch or self._thread is not None:
            return self
        self._stop.clear()
        self._note(self._read(), time.monotonic())
        self._thread = threading.Thread(target=self._run, name=self.name,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Idempotent; joins the watchdog thread (no leak, tier-1 guarded)."""
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=10.0)
        self._thread = None

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- internals ------------------------------------------------------------

    def _read(self) -> dict:
        with self._lock:
            watch = list(self._watch)
        out = {}
        for label, fn in watch:
            try:
                v = fn()
            except Exception:  # noqa: BLE001 — a heartbeat never kills the run
                self.dropped += 1
                continue
            if isinstance(v, dict):
                for k, x in v.items():
                    if isinstance(x, (int, float)) and not isinstance(x, bool):
                        out[f"{label}.{k}"] = x
            elif isinstance(v, (int, float)) and not isinstance(v, bool):
                out[label] = v
        return out

    def _note(self, vals: dict, now: float) -> float:
        """Fold one reading in; returns the newest per-lane change time.
        Locked: ``watch_consumer`` may drop a lane from another thread."""
        with self._lock:
            for lane, v in vals.items():
                rec = self._last.get(lane)
                if rec is None:
                    self._last[lane] = [v, now, False]
                elif v != rec[0]:
                    rec[0], rec[1], rec[2] = v, now, True
            return max((rec[1] for rec in self._last.values()), default=now)

    def _run(self) -> None:
        interval = min(max(self.hang_s / 4.0, 0.02), 1.0)
        while not self._stop.wait(interval):
            now = time.monotonic()
            newest = self._note(self._read(), now)
            if not self._last or now - newest <= self.hang_s:
                continue
            self._fire(now)
            if self.policy == "raise":
                return  # fired for good: the error is armed, nothing to re-watch
            with self._lock:
                for rec in self._last.values():  # log policy: re-arm
                    rec[1] = now

    def _fire(self, now: float) -> None:
        import logging

        self.fired = True
        with self._lock:
            ages = {lane: round(now - rec[1], 3)
                    for lane, rec in self._last.items()}
            moved = [l for l, rec in self._last.items() if rec[2]]
        pool = moved or list(ages)
        stalled_first = max(pool, key=lambda l: (ages[l], l)) if pool else None
        report = {
            "hang_s": self.hang_s,
            "policy": self.policy,
            "ages": ages,
            "stalled_first": stalled_first,
        }
        rec = self.recorder if self.recorder is not None else flight_recorder()
        try:
            self.last_dump = rec.dump(self.dump_path, reason="hang",
                                      watchdog=report)
        except Exception:  # noqa: BLE001 — an unwritable dump must not mask the hang
            self.last_dump = None
        msg = (f"watchdog: no watched lane advanced for {self.hang_s:g}s "
               f"(first stalled: {stalled_first}); "
               f"flight dump: {self.last_dump or '<unwritable>'}")
        logging.getLogger(__name__).warning(msg)
        if self.policy == "raise":
            from .errors import HangError

            err = HangError(msg, dump_path=self.last_dump)
            self.error = err
            with self._lock:
                hooks = list(self._abort_hooks)
            for h in hooks:
                try:
                    h(err)
                except Exception:  # noqa: BLE001
                    pass


# ---------------------------------------------------------------------------
# unified registry
# ---------------------------------------------------------------------------

# keys that are peaks/config, not flows: composition takes the max
_MERGE_MAXED = frozenset((
    "peak_in_flight_bytes", "window_peak_rows", "prefetch", "budget_bytes",
    "planner_link_mbps",
    # write section config: pool size composes by max, exactly as the
    # read side's prefetch does
    "workers",
    # serve section gauges: the cache footprint and the admission peak are
    # point-in-time state of ONE shared object, not flows to sum (the
    # names are serve-specific — a generic "bytes" here would max the
    # device section's h2d byte FLOW)
    "queue_depth_peak", "held_bytes", "capacity_bytes", "entries",
    # circuit-breaker gauge: circuits open RIGHT NOW on one board — two
    # snapshots of the same board must not sum
    "open_now",
    # tenant QoS gauges: configuration (weight, SLO target) and resident
    # state (cache bytes, the last computed retry hint) of one registry —
    # flows like submitted/rejected still sum; these must not
    "weight", "slo_p99_ms", "retry_after_hint_s", "cache_held_bytes",
))
# ratios/rates derived from the flows: summing them is meaningless (four
# files' overlap_efficiency is not their sum) — the merge drops them and
# as_dict() recomputes each from the merged numerators/denominators
_MERGE_DERIVED = frozenset((
    "overlap_efficiency", "rows_per_sec", "bytes_per_sec", "pages_per_chunk",
    "batches_per_sec",
))


def _merge_num_tree(dst: dict, src: dict) -> None:
    """Fold one numeric tree into another: dicts recurse, flows add, peaks
    and config take the max, derived ratios are dropped (recomputed at
    ``as_dict``), anything else last-writer-wins."""
    for k, v in src.items():
        if k in _MERGE_DERIVED:
            continue
        if isinstance(v, dict):
            _merge_num_tree(dst.setdefault(k, {}), v)
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            if k in _MERGE_MAXED:
                dst[k] = max(dst.get(k, 0), v)
            else:
                dst[k] = dst.get(k, 0) + v
        else:
            dst[k] = v


def _ratio(num, den, digits):
    return round(num / den, digits) if den else 0.0


def _recompute_derived(tree: dict) -> None:
    """Rebuild the `_MERGE_DERIVED` ratios of a composed tree from its
    merged flows, section by section (the formulas mirror PipelineStats /
    ReaderStats / LoaderStats properties)."""
    pipe, reader, loader = (tree.get("pipeline"), tree.get("reader"),
                            tree.get("loader"))
    if pipe:
        pipe["overlap_efficiency"] = _ratio(
            pipe.get("busy_seconds", 0.0), pipe.get("wall_seconds", 0.0), 3)
    if reader:
        wall = reader.get("wall_seconds", 0.0)
        reader["rows_per_sec"] = _ratio(reader.get("rows", 0), wall, 1)
        reader["bytes_per_sec"] = _ratio(
            reader.get("compressed_bytes", 0), wall, 1)
        reader["pages_per_chunk"] = _ratio(
            reader.get("pages", 0), reader.get("chunks", 0), 3)
    if loader:
        wall = loader.get("wall_seconds", 0.0)
        loader["rows_per_sec"] = _ratio(loader.get("rows", 0), wall, 1)
        loader["batches_per_sec"] = _ratio(loader.get("batches", 0), wall, 3)
    write = tree.get("write")
    if write:
        wall = write.get("wall_seconds", 0.0)
        write["rows_per_sec"] = _ratio(write.get("rows", 0), wall, 1)
        write["bytes_per_sec"] = _ratio(
            write.get("bytes_written", 0), wall, 1)


class StatsRegistry:
    """One versioned tree over every stats surface the engine already has.

    Sources accumulate (``add_*`` may be called once per reader/file of a
    multi-file scan); ``as_dict()`` snapshots the composition.  The tree is
    versioned (``obs_version``) and golden-key-tested so bench parsers and
    the driver can't silently break on key drift.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._pipeline: "dict | None" = None
        self._reader: "dict | None" = None
        self._loader: "dict | None" = None
        self._io: "dict | None" = None
        self._data_errors: "dict | None" = None
        self._device: "dict | None" = None
        self._serve: "dict | None" = None
        self._cache: "dict | None" = None
        self._write: "dict | None" = None
        self._alloc_peak = 0
        self._alloc_device_peak = 0
        self._hists: dict[str, LatencyHistogram] = {}

    # -- composition ----------------------------------------------------------

    def histogram(self, name: str) -> LatencyHistogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = LatencyHistogram()
            return h

    def add_pipeline(self, pstats) -> None:
        """Fold a :class:`~tpu_parquet.pipeline.PipelineStats` in (its
        per-stage histograms become registry histograms ``stage.<name>``)."""
        d = pstats.as_dict()
        hists = d.pop("stage_histograms", {})
        with self._lock:
            if self._pipeline is None:
                self._pipeline = {}
            _merge_num_tree(self._pipeline, d)
        for stage, hd in hists.items():
            self.histogram(f"stage.{stage}").merge_dict(hd)

    def add_reader(self, rstats) -> None:
        """Fold a :class:`~tpu_parquet.device_reader.ReaderStats` in."""
        with self._lock:
            if self._reader is None:
                self._reader = {}
            _merge_num_tree(self._reader, rstats.as_dict())

    def add_loader(self, lstats) -> None:
        """Fold a :class:`~tpu_parquet.data.loader.LoaderStats` in (its
        nested pipeline section routes to the pipeline composition)."""
        d = lstats.as_dict()
        pipe = d.pop("pipeline", None)
        with self._lock:
            if self._loader is None:
                self._loader = {}
            _merge_num_tree(self._loader, d)
        if pipe is not None:
            self.add_pipeline(lstats.pipeline)

    def add_io(self, iostats) -> None:
        """Fold a :class:`~tpu_parquet.iostore.IOStats` in (retry/backoff/
        coalescing counters of one store; all flows, so multi-file scans
        compose by addition).  Raw dicts accepted for tests."""
        d = iostats if isinstance(iostats, dict) else iostats.as_dict()
        with self._lock:
            if self._io is None:
                self._io = {}
            _merge_num_tree(self._io, d)

    def add_data_errors(self, quarantine) -> None:
        """Fold a :class:`~tpu_parquet.quarantine.Quarantine`'s counters in
        (the ``data_errors`` section: errors / units_skipped / rows_skipped /
        files_skipped / by_class — all flows, so multi-engine scans compose
        by addition).  Raw dicts accepted for tests."""
        d = (quarantine if isinstance(quarantine, dict)
             else quarantine.as_dict())
        with self._lock:
            if self._data_errors is None:
                self._data_errors = {}
            _merge_num_tree(self._data_errors, d)

    def add_device(self, devstats) -> None:
        """Fold a :class:`~tpu_parquet.device_reader.DeviceStats` in (the
        ``device`` section: per-route and per-kernel-family completion-side
        dispatch timing plus the h2d transfer lane — all flows, so
        multi-file scans compose by addition).  Raw dicts accepted for
        tests and cross-process merges."""
        d = devstats if isinstance(devstats, dict) else devstats.as_dict()
        with self._lock:
            if self._device is None:
                self._device = {}
            _merge_num_tree(self._device, d)

    def add_serve(self, serve_stats) -> None:
        """Fold a :class:`~tpu_parquet.serve.ServeStats` tree in (the
        ``serve`` section: request/rejection counters, queue-wait and exec
        second sums, and the plan-cache hit/miss/eviction counters — all
        flows except the ``queue_depth_peak``/cache-gauge keys, which the
        generic merge already treats per its rules).  Raw dicts accepted
        for tests and cross-process merges."""
        d = (serve_stats if isinstance(serve_stats, dict)
             else serve_stats.as_dict())
        with self._lock:
            if self._serve is None:
                self._serve = {}
            _merge_num_tree(self._serve, d)

    def add_cache(self, cache_counters) -> None:
        """Fold a :class:`~tpu_parquet.serve.ResultCache`'s counters in
        (the ``cache`` section: per-tier hit/miss/eviction/invalidation
        flows, ``held_bytes``/``capacity_bytes``/``entries`` gauges — the
        generic merge maxes those by name — and the single-flight wait
        count).  Raw dicts accepted (they are the native form)."""
        d = (cache_counters if isinstance(cache_counters, dict)
             else cache_counters.counters())
        with self._lock:
            if self._cache is None:
                self._cache = {}
            _merge_num_tree(self._cache, d)

    def add_write(self, write_stats) -> None:
        """Fold a :class:`~tpu_parquet.write.WriteStats` in (the ``write``
        section: encode/compress/flush/merge/compact lane seconds plus
        row/file/byte flows — all flows except ``workers``, which maxes
        like the read side's ``prefetch``).  Its per-stage histograms
        become registry histograms ``write.<stage>``.  Raw dicts accepted
        for tests and cross-process merges."""
        d = (write_stats if isinstance(write_stats, dict)
             else write_stats.as_dict())
        d = dict(d)
        hists = d.pop("stage_histograms", {})
        with self._lock:
            if self._write is None:
                self._write = {}
            _merge_num_tree(self._write, d)
        for stage, hd in hists.items():
            self.histogram(f"write.{stage}").merge_dict(hd)

    def note_alloc_peak(self, tracker) -> None:
        """Record an :class:`~tpu_parquet.alloc.AllocTracker`'s high-water
        marks (host ``peak`` + device-bytes ``device_peak``; raw ints
        accepted for tests as the host peak alone)."""
        peak = int(getattr(tracker, "peak", tracker or 0))
        dev_peak = int(getattr(tracker, "device_peak", 0) or 0)
        with self._lock:
            self._alloc_peak = max(self._alloc_peak, peak)
            self._alloc_device_peak = max(self._alloc_device_peak, dev_peak)

    def merge_from(self, other: "StatsRegistry") -> None:
        with other._lock:
            pipeline = dict(other._pipeline) if other._pipeline else None
            reader = dict(other._reader) if other._reader else None
            loader = dict(other._loader) if other._loader else None
            io = dict(other._io) if other._io else None
            data_errors = (dict(other._data_errors)
                           if other._data_errors else None)
            device = dict(other._device) if other._device else None
            serve = dict(other._serve) if other._serve else None
            cache = dict(other._cache) if other._cache else None
            write = dict(other._write) if other._write else None
            peak = other._alloc_peak
            dev_peak = other._alloc_device_peak
            hists = dict(other._hists)
        with self._lock:
            for name, src in (("_pipeline", pipeline), ("_reader", reader),
                              ("_loader", loader), ("_io", io),
                              ("_data_errors", data_errors),
                              ("_device", device), ("_serve", serve),
                              ("_cache", cache), ("_write", write)):
                if src is None:
                    continue
                dst = getattr(self, name)
                if dst is None:
                    setattr(self, name, dst := {})
                _merge_num_tree(dst, src)
            self._alloc_peak = max(self._alloc_peak, peak)
            self._alloc_device_peak = max(self._alloc_device_peak, dev_peak)
        for name, h in hists.items():
            self.histogram(name).merge_from(h)

    def merge_dict(self, tree: dict) -> None:
        """Fold a serialized registry tree (another process's) in."""
        if tree.get("obs_version") != OBS_VERSION:
            raise ValueError(
                f"obs_version {tree.get('obs_version')!r} != {OBS_VERSION}")
        for key, attr in (("pipeline", "_pipeline"), ("reader", "_reader"),
                          ("loader", "_loader"), ("io", "_io"),
                          ("data_errors", "_data_errors"),
                          ("device", "_device"), ("serve", "_serve"),
                          ("cache", "_cache"), ("write", "_write")):
            src = tree.get(key)
            if src is None:
                continue
            src = dict(src)
            src.pop("ship_feedback", None)
            with self._lock:
                dst = getattr(self, attr)
                if dst is None:
                    setattr(self, attr, dst := {})
                _merge_num_tree(dst, src)
        with self._lock:
            alloc = tree.get("alloc", {})
            self._alloc_peak = max(self._alloc_peak,
                                   int(alloc.get("peak_bytes", 0)))
            self._alloc_device_peak = max(
                self._alloc_device_peak,
                int(alloc.get("device_peak_bytes", 0) or 0))
        for name, hd in tree.get("histograms", {}).items():
            self.histogram(name).merge_dict(hd)

    # -- reporting ------------------------------------------------------------

    def ship_feedback(self) -> dict:
        """Per-route predicted vs measured link-lane seconds.

        Predicted: the ship planner's modeled bottleneck-lane seconds for
        each stream's CHOSEN route (summed per route — ReaderStats carries
        them next to the byte counters).  Measured: the route's shipped
        bytes through the link rate this run actually achieved
        (staged bytes / stage-stage seconds — the staging span IS the link
        lane).  ``error_ratio`` = measured/predicted: >1 means the model
        was optimistic (raise ``TPQ_LINK_MBPS``'s denominator — i.e. the
        link was slower than planned), <1 pessimistic.

        The ``measured_seconds``/``error_ratio`` keys are always present:
        a route chosen by the planner but never timed (a forced route with
        tracing off, a run whose staging span recorded no seconds) reports
        ``null`` — explicitly unmeasured, never a divide-by-zero or a bogus
        0.0 ratio a diff would read as "infinitely fast".

        The DEVICE lane rides each route the same way: predicted device
        seconds from the planner's device cost term
        (``predicted_device_s`` on ReaderStats), measured from the
        completion-side device timing (the ``device`` section's per-route
        ``device_seconds``, ``TPQ_DEVICE_TIMING``) — null when the timing
        lane never ran, same contract as the link lane.
        """
        with self._lock:
            reader = dict(self._reader or {})
            pipeline = dict(self._pipeline or {})
            device = dict(self._device or {})
        routes = reader.get("ship_routes") or {}
        staged = reader.get("staged_bytes") or 0
        stage_s = pipeline.get("stage_seconds") or 0.0
        link_bps = staged / stage_s if staged and stage_s else 0.0
        dev_routes = device.get("routes") or {}
        out = {}
        for route, c in sorted(routes.items()):
            # null-check and ratio on the RAW values, display rounding last:
            # a 100-byte stream on a fast link measures ~1e-7s, which
            # round(..., 6) flattens to exactly the bogus-0.0 this contract
            # exists to rule out
            pred = float(c.get("predicted_s", 0.0))
            meas = c.get("shipped", 0) / link_bps if link_bps else None
            dev_pred = float(c.get("predicted_device_s", 0.0) or 0.0)
            dr = dev_routes.get(route) or {}
            dev_meas = (float(dr["device_seconds"])
                        if dr.get("dispatches") else None)
            # fused routes carry the UNFUSED chain's device prediction too
            # (null elsewhere) — the fusion-win comparison's bar
            unf = float(c.get("predicted_unfused_device_s", 0.0) or 0.0)
            out[route] = {
                "streams": c.get("streams", 0),
                "shipped_bytes": c.get("shipped", 0),
                "predicted_seconds": round(pred, 9),
                "measured_seconds": (round(meas, 9) if meas is not None
                                     else None),
                "error_ratio": (round(meas / pred, 3)
                                if meas is not None and pred else None),
                "device_predicted_seconds": round(dev_pred, 9),
                "device_measured_seconds": (round(dev_meas, 9)
                                            if dev_meas is not None
                                            else None),
                "device_error_ratio": (round(dev_meas / dev_pred, 3)
                                       if dev_meas is not None and dev_pred
                                       else None),
                "device_unfused_predicted_seconds": (round(unf, 9)
                                                     if unf else None),
            }
        return {"link_bytes_per_sec": round(link_bps, 1), "routes": out}

    def as_dict(self) -> dict:
        with self._lock:
            tree = {
                "obs_version": OBS_VERSION,
                "pipeline": dict(self._pipeline) if self._pipeline else None,
                "reader": dict(self._reader) if self._reader else None,
                "loader": dict(self._loader) if self._loader else None,
                "io": dict(self._io) if self._io else None,
                "data_errors": (dict(self._data_errors)
                                if self._data_errors else None),
                "device": dict(self._device) if self._device else None,
                "serve": dict(self._serve) if self._serve else None,
                "cache": dict(self._cache) if self._cache else None,
                "write": dict(self._write) if self._write else None,
                "alloc": {"peak_bytes": self._alloc_peak,
                          "device_peak_bytes": self._alloc_device_peak},
                "histograms": {n: h.as_dict()
                               for n, h in sorted(self._hists.items())},
            }
        _recompute_derived(tree)
        if tree["reader"] is not None:
            tree["reader"]["ship_feedback"] = self.ship_feedback()
        return tree

    def render_openmetrics(self) -> str:
        """OpenMetrics text exposition of the live tree (see
        :func:`render_openmetrics`)."""
        return render_openmetrics(self.as_dict())


# ---------------------------------------------------------------------------
# OpenMetrics export: text exposition + periodic snapshot dumper
# ---------------------------------------------------------------------------

def _om_name(*parts) -> str:
    """A legal OpenMetrics metric name from tree-path parts."""
    name = "_".join(str(p) for p in parts if p not in (None, ""))
    out = []
    for ch in name:
        out.append(ch if (ch.isascii() and (ch.isalnum() or ch == "_"))
                   else "_")
    name = "".join(out) or "_"
    return name if not name[0].isdigit() else f"_{name}"


def _om_escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _om_num(v) -> str:
    # integral floats render as ints: counter samples read naturally and
    # snapshots diff cleanly
    if isinstance(v, float) and not v.is_integer():
        return repr(v)
    return str(int(v))


def _om_walk(lines: list, prefix: "tuple", tree: dict) -> None:
    for k, v in sorted(tree.items()):
        if isinstance(v, dict):
            _om_walk(lines, prefix + (k,), v)
        elif isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        else:
            name = _om_name("tpq", *prefix, k)
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {_om_num(v)}")


def render_openmetrics(tree: dict) -> str:
    """Render a registry tree (``StatsRegistry.as_dict`` form) as an
    OpenMetrics text exposition: every numeric leaf as a gauge
    ``tpq_<section>_<path>``, every histogram as a cumulative-``le``
    bucket family with ``_sum``/``_count`` — and, where a bucket carries a
    retained-trace exemplar, the OpenMetrics exemplar suffix
    ``# {trace_id="..."} value`` that lets a dashboard jump from a bucket
    straight to ``pq_tool trace --request``.  Ends with ``# EOF``.
    """
    if not isinstance(tree, dict):
        raise ValueError("not a registry tree")
    lines: list[str] = []
    for section in ("pipeline", "reader", "loader", "io", "data_errors",
                    "device", "serve", "cache", "write", "alloc"):
        sub = tree.get(section)
        if isinstance(sub, dict):
            sub = dict(sub)
            sub.pop("ship_feedback", None)  # ratios with nulls, not samples
            _om_walk(lines, (section,), sub)
    for hname, hd in sorted((tree.get("histograms") or {}).items()):
        if not isinstance(hd, dict):
            continue
        name = _om_name("tpq", hname, "seconds")
        lines.append(f"# TYPE {name} histogram")
        exemplars = hd.get("exemplars") or {}
        cum = 0
        for i in sorted(int(k) for k in (hd.get("buckets") or {})):
            cum += int(hd["buckets"][str(i)])
            le = LatencyHistogram.bucket_upper_seconds(i)
            line = f'{name}_bucket{{le="{le!r}"}} {cum}'
            ex = exemplars.get(str(i))
            if isinstance(ex, (list, tuple)) and len(ex) == 2:
                line += (f' # {{trace_id="{_om_escape(ex[0])}"}}'
                         f" {float(ex[1])!r}")
            lines.append(line)
        lines.append(f'{name}_bucket{{le="+Inf"}} {int(hd.get("count", 0))}')
        lines.append(f'{name}_sum {float(hd.get("sum_seconds", 0.0))!r}')
        lines.append(f'{name}_count {int(hd.get("count", 0))}')
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def diff_registry_trees(old: dict, new: dict) -> dict:
    """Numeric-leaf deltas between two registry snapshots (``pq_tool
    metrics A B`` / ``--watch``): ``{dotted.path: (old, new, delta)}`` for
    every leaf that changed, sections and histograms alike."""

    def leaves(tree, prefix, out):
        if isinstance(tree, dict):
            for k, v in tree.items():
                leaves(v, f"{prefix}.{k}" if prefix else str(k), out)
        elif isinstance(tree, (int, float)) and not isinstance(tree, bool):
            out[prefix] = tree

    a: dict = {}
    b: dict = {}
    leaves(old, "", a)
    leaves(new, "", b)
    out = {}
    for key in sorted(set(a) | set(b)):
        va, vb = a.get(key, 0), b.get(key, 0)
        if va != vb:
            out[key] = (va, vb, vb - va)
    return out


def resolve_metrics_dump(spec: "str | None" = None):
    """Parse a ``path:interval_s`` metrics-dump spec (default:
    ``TPQ_METRICS_DUMP``).  Returns ``(path, interval_s)`` or ``None``;
    malformed values degrade with one :func:`warn_env_once` line, never
    raise (the env-knob contract)."""
    raw = os.environ.get("TPQ_METRICS_DUMP", "") if spec is None else spec
    if not raw:
        return None
    path, sep, interval = raw.rpartition(":")
    if not sep or not path:
        warn_env_once("TPQ_METRICS_DUMP", raw, None)
        return None
    try:
        iv = float(interval)
    except (TypeError, ValueError):
        warn_env_once("TPQ_METRICS_DUMP", raw, None)
        return None
    if iv <= 0:
        warn_env_once("TPQ_METRICS_DUMP", raw, None)
        return None
    return path, iv


class MetricsDumper:
    """Daemon thread writing periodic registry snapshots to disk
    (``TPQ_METRICS_DUMP=path:interval_s``) — the live scrape surface
    ``pq_tool metrics --watch`` polls.

    ``source`` is a zero-arg callable returning a :class:`StatsRegistry`
    or an ``as_dict`` tree; each tick writes the JSON tree atomically
    (tmp + ``os.replace`` — a watcher never reads a torn file).  Lifecycle
    discipline matches :class:`Sampler`: inert when the spec is unset or
    malformed, ``stop()`` joins (and writes one final snapshot so the file
    ends at the end state), the thread is a daemon, and a failing source
    or write is counted, never raised.
    """

    def __init__(self, source, spec: "str | None" = None,
                 name: str = "tpq-metricsdump"):
        self.source = source
        parsed = resolve_metrics_dump(spec)
        self.path, self.interval_s = parsed if parsed else (None, 0.0)
        self.name = name
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self.written = 0
        self.dropped = 0

    @property
    def enabled(self) -> bool:
        return self.path is not None and self.interval_s > 0

    def start(self) -> "MetricsDumper":
        if not self.enabled or self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name=self.name,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Idempotent; joins the dumper thread (no leak, bench-gated)."""
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=10.0)
        self._thread = None

    def __enter__(self) -> "MetricsDumper":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _run(self) -> None:
        while True:
            stopping = self._stop.wait(self.interval_s)
            self.dump_once()
            if stopping:
                return

    def dump_once(self) -> "str | None":
        if self.path is None:
            return None
        try:
            tree = self.source()
            if isinstance(tree, StatsRegistry):
                tree = tree.as_dict()
            parent = os.path.dirname(self.path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(tree, f, default=repr)
                f.write("\n")
            os.replace(tmp, self.path)
            self.written += 1
            return self.path
        except Exception:  # noqa: BLE001 — metrics export never takes the run down
            self.dropped += 1
            return None


# ---------------------------------------------------------------------------
# trace summarization (the pq_tool backend)
# ---------------------------------------------------------------------------

# the span names PipelineStats.timed emits — the busy-seconds basis of
# overlap efficiency, kept in lockstep with pipeline.STAGES by test_obs
PIPELINE_SPAN_NAMES = ("io", "decompress", "recompress", "stage", "dispatch",
                       "finalize")


def _exact_quantile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def trace_summary(doc) -> dict:
    """Aggregate a Chrome trace-event document (object or bare-array form)
    into the per-stage/overlap/stall/route report ``pq_tool trace`` prints.

    Works from the trace alone: stage stats come from the ``X`` spans
    (exact p50/p95 over the recorded durations — the full population is in
    hand, no histogram approximation needed), overlap efficiency is
    busy/wall over the pipeline span names, stall attribution from the
    ``stall`` spans, and route prediction error from the ``ship`` instants'
    args against the measured link lane (staged bytes / stage seconds).
    """
    if isinstance(doc, dict):
        events = doc.get("traceEvents")
        other = doc.get("otherData") or {}
    else:
        events, other = doc, {}
    if not isinstance(events, list):
        raise ValueError("not a trace-event document: no traceEvents array")
    spans: dict[str, list[float]] = {}
    ships: list[dict] = []
    t_min, t_max = None, None
    n_threads = set()
    pipe_walls: dict = {}  # (pid, pipe-token) -> that pipeline's max wall
    for ev in events:
        if not isinstance(ev, dict):
            raise ValueError("malformed trace event (not an object)")
        ph = ev.get("ph")
        if ph == "M":
            continue
        n_threads.add((ev.get("pid"), ev.get("tid")))
        ts = ev.get("ts")
        if ts is None:
            continue
        end = ts + ev.get("dur", 0)
        t_min = ts if t_min is None else min(t_min, ts)
        t_max = end if t_max is None else max(t_max, end)
        if ph == "X":
            spans.setdefault(ev.get("name", "?"), []).append(
                ev.get("dur", 0) / 1e6)
        elif ph == "i" and ev.get("name") == "ship":
            ships.append(ev.get("args") or {})
        elif ph == "C" and ev.get("name") == "pipeline_wall":
            args = ev.get("args") or {}
            key = (ev.get("pid"), args.get("pipe"))
            pipe_walls[key] = max(pipe_walls.get(key, 0.0),
                                  float(args.get("seconds", 0)))
    # the overlap denominator: the PipelineStats wall clocks when they rode
    # the trace — each stats object's counter is cumulative, so take its
    # max, then SUM across objects (one per file of a scan: sequential
    # segments whose busy spans the numerator also sums).  Falls back to
    # the span extent for traces with no pipeline counters.
    pipe_wall = sum(pipe_walls.values())
    wall = pipe_wall or ((t_max - t_min) / 1e6 if t_min is not None else 0.0)
    stages = {}
    for name, durs in sorted(spans.items()):
        durs.sort()
        stages[name] = {
            "count": len(durs),
            "total_seconds": round(sum(durs), 6),
            "p50_seconds": round(_exact_quantile(durs, 0.50), 9),
            "p95_seconds": round(_exact_quantile(durs, 0.95), 9),
            "max_seconds": round(durs[-1], 9),
        }
    busy = sum(stages[s]["total_seconds"] for s in PIPELINE_SPAN_NAMES
               if s in stages)
    stall = stages.get("stall", {}).get("total_seconds", 0.0)
    # measured link lane: the stage spans carry their staged byte counts
    stage_bytes = 0
    for ev in events:
        if ev.get("ph") == "X" and ev.get("name") == "stage":
            stage_bytes += (ev.get("args") or {}).get("bytes", 0)
    stage_s = stages.get("stage", {}).get("total_seconds", 0.0)
    link_bps = stage_bytes / stage_s if stage_bytes and stage_s else 0.0
    routes: dict[str, dict] = {}
    for s in ships:
        r = routes.setdefault(str(s.get("route", "?")), {
            "streams": 0, "logical_bytes": 0, "shipped_bytes": 0,
            "predicted_seconds": 0.0, "device_predicted_seconds": 0.0,
        })
        r["streams"] += 1
        r["logical_bytes"] += int(s.get("logical", 0))
        r["shipped_bytes"] += int(s.get("shipped", 0))
        r["predicted_seconds"] += float(s.get("predicted_s", 0.0))
        r["device_predicted_seconds"] += float(
            s.get("predicted_device_s", 0.0) or 0.0)
    for name, r in routes.items():
        # keys always present; null = unmeasured (same contract as
        # StatsRegistry.ship_feedback — never a fake 0.0 ratio, so the
        # ratio and the null check use the RAW values, rounding last)
        pred = r["predicted_seconds"]
        meas = r["shipped_bytes"] / link_bps if link_bps else None
        r["predicted_seconds"] = round(pred, 9)
        r["measured_seconds"] = round(meas, 9) if meas is not None else None
        r["error_ratio"] = (round(meas / pred, 3)
                            if meas is not None and pred else None)
        # the device lane: completion-side `device.<route>` spans (the
        # TPQ_DEVICE_TIMING worker emits one per dispatch).  Same null
        # contract — a run with the timing lane off reports null, and an
        # artifact predating it can never KeyError.
        dev_pred = r["device_predicted_seconds"]
        dev = spans.get(f"device.{name}")
        dev_meas = sum(dev) if dev else None
        r["device_predicted_seconds"] = round(dev_pred, 9)
        r["device_measured_seconds"] = (round(dev_meas, 9)
                                        if dev_meas is not None else None)
        r["device_error_ratio"] = (round(dev_meas / dev_pred, 3)
                                   if dev_meas is not None and dev_pred
                                   else None)
    return {
        "obs_version": other.get("obs_version"),
        "events": len(events),
        "threads": len(n_threads),
        "wall_seconds": round(wall, 6),
        "busy_seconds": round(busy, 6),
        "overlap_efficiency": round(busy / wall, 3) if wall else 0.0,
        "stall_seconds": round(stall, 6),
        "stall_share": round(stall / wall, 4) if wall else 0.0,
        "stages": stages,
        "link_bytes_per_sec": round(link_bps, 1),
        "routes": dict(sorted(routes.items())),
        "registry": other.get("registry"),
    }


# ---------------------------------------------------------------------------
# doctor: rule-based bottleneck attribution (the pq_tool doctor backend)
# ---------------------------------------------------------------------------

# the verdicts `pq_tool doctor` can return, keyed by lane
DOCTOR_VERDICTS = {
    "link": "link-bound",
    "host_decompress": "host-decompress-bound",
    "stall": "stall-bound",
    "device_resolve": "device-resolve-bound",
    "h2d": "h2d-bound",
    "admission": "admission-bound",
}
# routes whose overall error_ratio leaves this band disagree with the cost
# model enough that re-running with the recalibrated TPQ_LINK_MBPS is the
# next step (inside it, re-banking changes no route choice worth chasing)
DOCTOR_ERROR_BAND = (0.8, 1.25)
# hedging advisory thresholds: below this many issued hedges the win rate
# is noise; below this win rate with wasted bytes on the books the hedge
# delay is mis-set (too aggressive) and doctor says so
HEDGE_VERDICT_MIN_ISSUED = 8
HEDGE_VERDICT_MIN_WIN_RATE = 0.2
# cache-thrash advisory thresholds (the result cache's `cache` section):
# a tier evicting at least this many entries while serving under this hit
# rate is churning — the working set does not fit its byte budget, and
# doctor names the tier (raise TPQ_RESULT_CACHE_MB / _HBM_MB) and the
# top-evicting file (or shard it) instead of letting the tier burn decode
# work it immediately throws away
CACHE_THRASH_MIN_EVICTIONS = 8
CACHE_THRASH_MAX_HIT_RATE = 0.5
# io-concurrency advisory thresholds (the async fetch engine's
# ``io.engine`` subtree): with the io lane dominant, ranges spending at
# least IO_CONC_QUEUE_WAIT_RATIO× as long waiting for an in-flight slot
# as actually fetching means concurrency — not the store — is the
# bottleneck.  A peak within IO_CONC_PIN_FRACTION of the engine cap names
# TPQ_IO_INFLIGHT; a peak pinned at the decode window instead names
# ``prefetch=`` (the feed could not submit deeper than decode allowed).
# Fewer than IO_CONC_MIN_FETCHES finished fetches is noise, not evidence.
IO_CONC_MIN_FETCHES = 16
IO_CONC_PIN_FRACTION = 0.9
IO_CONC_QUEUE_WAIT_RATIO = 2.0
# overload advisory threshold: fewer rejects+sheds than this is routine
# backpressure noise, not a verdict.  At or above it doctor names the
# tenant with the largest demand (submitted + rejected) as the offender
# and lists the tenants that ate rejections alongside it — the operator's
# next step is that tenant's weight/budget, not a global knob
OVERLOAD_MIN_REJECTS = 4


def _slo_burn_block(serve: dict, tree: dict) -> "dict | None":
    """The ``slo-burn`` verdict: a tenant whose measured p99 (its
    ``serve.tenant.<name>`` histogram) exceeds its declared ``slo_p99_ms``.
    Names the worst offender (largest p99/SLO ratio), the offending
    bucket, and — when the tail sampler linked one — the exemplar trace id
    that turns the bad percentile into a ``pq_tool trace --request``."""
    tens = {n: t for n, t in (serve.get("tenants") or {}).items()
            if isinstance(t, dict)}
    hists = tree.get("histograms")
    hists = hists if isinstance(hists, dict) else {}
    burns = []
    for name, t in sorted(tens.items()):
        slo_ms = t.get("slo_p99_ms")
        slo_ms = float(slo_ms) if isinstance(slo_ms, (int, float)) else 0.0
        hd = hists.get(f"serve.tenant.{name}")
        if slo_ms <= 0 or not isinstance(hd, dict) or not hd.get("count"):
            continue
        p99 = LatencyHistogram.from_dict(hd).quantile(0.99)
        if p99 * 1e3 <= slo_ms:
            continue
        # the offending bucket: the slowest populated bucket at/above the
        # SLO bound — where the burn actually lives (never the fast body)
        slo_idx = LatencyHistogram.bucket_index(slo_ms / 1e3)
        pop = [i for i, n in ((int(k), int(v))
                              for k, v in (hd.get("buckets") or {}).items())
               if n > 0]
        over = [i for i in pop if i >= slo_idx]
        bucket = max(over) if over else (max(pop) if pop else 0)
        ex = (hd.get("exemplars") or {}).get(str(bucket))
        burns.append({
            "tenant": name,
            "slo_p99_ms": round(slo_ms, 3),
            "p99_ms": round(p99 * 1e3, 3),
            "burn_ratio": round(p99 * 1e3 / slo_ms, 3),
            "bucket": bucket,
            "bucket_le_s": round(
                LatencyHistogram.bucket_upper_seconds(bucket), 9),
            "exemplar_trace": (str(ex[0])
                               if isinstance(ex, (list, tuple)) and ex
                               else None),
            "exemplar_value_s": (round(float(ex[1]), 6)
                                 if isinstance(ex, (list, tuple))
                                 and len(ex) == 2 else None),
        })
    if not burns:
        return None
    burns.sort(key=lambda b: (-b["burn_ratio"], b["tenant"]))
    worst = burns[0]
    ex_hint = (f"; pq_tool trace --request {worst['exemplar_trace']} "
               f"prints the retained trace"
               if worst["exemplar_trace"] else
               "; no exemplar retained yet (raise sampling: TPQ_TRACE_TAIL)")
    return {
        "verdict": "slo-burn",
        **worst,
        "burning_tenants": [b["tenant"] for b in burns],
        "advice": (
            f"tenant '{worst['tenant']}' p99 {worst['p99_ms']:g}ms exceeds "
            f"its {worst['slo_p99_ms']:g}ms SLO ({worst['burn_ratio']}x); "
            f"the burn sits in bucket {worst['bucket']} "
            f"(<= {worst['bucket_le_s']:g}s){ex_hint}"),
    }


def doctor_registry(tree: dict) -> "dict | None":
    """Attribute a run's bottleneck from its registry tree (rule-based).

    The overlapped pipeline runs four lanes concurrently; steady-state wall
    time is the *largest* lane, so the verdict is simply the lane with the
    most recorded seconds:

    - ``link``            ``stage_seconds`` (host->device staging — the
      transfers themselves)
    - ``host_decompress``  ``io + decompress + recompress`` seconds (the
      host's half of the work; falls back to the reader's ``host_seconds``
      for prefetch=0 runs that never routed through the chunk pool)
    - ``device_resolve``  the measured per-route device completion seconds
      (the ``device`` registry section, ``TPQ_DEVICE_TIMING``); falls back
      to ``dispatch + finalize`` host-side seconds for artifacts predating
      the device section (never a KeyError — old records stay readable)
    - ``h2d``             measured h2d transfer completion seconds (the
      ``device`` section's ``h2d`` lane; 0 for old artifacts, so the new
      verdict can never fire on a record that carries no evidence for it)
    - ``stall``           budget backpressure (the submitter blocked on
      ``max_memory`` — more memory or less lookahead, not more bandwidth)

    Folds in ``ship_feedback()``: when the routes' measured link-lane
    seconds disagree with the planner's predictions beyond
    ``DOCTOR_ERROR_BAND``, the report carries ``recalibrate_link_mbps`` —
    the measured staging rate as the ``TPQ_LINK_MBPS`` value to re-run
    with (exactly the 1B re-measure procedure in ROADMAP item 1).  With a
    ``device`` section, the report additionally carries a ``device`` block
    naming the dominant device route and kernel family with its
    predicted-vs-measured error ratio, and ``recalibrate_device_mbps``
    when that ratio leaves the band — the device twin of the link loop.

    Returns ``None`` when the tree has no lane seconds to attribute.
    """
    if not isinstance(tree, dict):
        return None
    pipe = tree.get("pipeline") or {}
    reader = tree.get("reader") or {}
    if not isinstance(pipe, dict) or not isinstance(reader, dict):
        return None
    dev = tree.get("device")
    dev = dev if isinstance(dev, dict) else {}
    serve = tree.get("serve")
    serve = serve if isinstance(serve, dict) else {}

    def g(d, k):
        v = d.get(k)
        return float(v) if isinstance(v, (int, float)) else 0.0

    host = (g(pipe, "io_seconds") + g(pipe, "decompress_seconds")
            + g(pipe, "recompress_seconds"))
    if host == 0.0:
        host = g(reader, "host_seconds")
    dev_routes = {r: c for r, c in (dev.get("routes") or {}).items()
                  if isinstance(c, dict)}
    dev_resolve = sum(g(c, "device_seconds") for c in dev_routes.values())
    lanes = {
        "link": g(pipe, "stage_seconds"),
        "host_decompress": host,
        # measured completion seconds when the timing lane ran; the
        # host-side dispatch+finalize wall otherwise (old artifacts,
        # TPQ_DEVICE_TIMING=0 runs)
        "device_resolve": dev_resolve or (g(pipe, "dispatch_seconds")
                                          + g(pipe, "finalize_seconds")),
        "h2d": g(dev.get("h2d") or {}, "device_seconds"),
        "stall": g(pipe, "stall_seconds"),
        # the serve section's queue-wait sum: requests waiting for a worker
        # slot.  Dominant queue-wait means the service is admission-bound —
        # raise TPQ_SERVE_CONCURRENCY (or shed load earlier), the decode
        # lanes are not the problem (records without a serve section carry
        # a 0 here, so the verdict can never fire on old artifacts)
        "admission": g(serve, "queue_wait_seconds"),
    }
    total = sum(lanes.values())
    wr = tree.get("write")
    wr = wr if isinstance(wr, dict) else {}
    wr_lanes = {s: g(wr, f"{s}_seconds")
                for s in ("encode", "compress", "flush", "merge", "compact")}
    wr_lanes["stall"] = g(wr, "stall_seconds")
    wr_total = sum(wr_lanes.values())
    _sheds = serve.get("sheds")
    _sheds = _sheds if isinstance(_sheds, dict) else {}
    overload_pressure = (g(serve, "rejected") + g(_sheds, "low")
                         + g(_sheds, "normal"))
    slo_burn = _slo_burn_block(serve, tree)
    if total <= 0 and wr_total <= 0:
        # no decode/write lane ran — but a service rejecting work IS
        # evidence: an overload where nothing got far enough to decode is
        # exactly when the operator reaches for doctor, and a tenant
        # burning its SLO is evidence the same way
        if overload_pressure < OVERLOAD_MIN_REJECTS and slo_burn is None:
            return None
    out: dict = {}
    if total > 0:
        dominant = max(lanes, key=lambda k: (lanes[k], k))
        out = {
            "lanes": {k: round(v, 6) for k, v in lanes.items()},
            "dominant_lane": dominant,
            "verdict": DOCTOR_VERDICTS[dominant],
            "dominant_share": round(lanes[dominant] / total, 4),
        }
    if dev_routes:
        # name the dominant device route (and kernel family) with its
        # predicted-vs-measured error — the fused-kernel work (ROADMAP
        # direction 2) starts from exactly this attribution
        routes_pred = reader.get("ship_routes") or {}
        dom_route = max(dev_routes,
                        key=lambda r: (g(dev_routes[r], "device_seconds"), r))
        dm = g(dev_routes[dom_route], "device_seconds")
        dp = float((routes_pred.get(dom_route) or {})
                   .get("predicted_device_s") or 0.0)
        kernels = {k: c for k, c in (dev.get("kernels") or {}).items()
                   if isinstance(c, dict)}
        dom_kernel = (max(kernels,
                          key=lambda k: (g(kernels[k], "device_seconds"), k))
                      if kernels else None)
        # the recalibration rate comes from the DOMINANT route alone — a
        # blend across routes (plain's near-zero-compute bytes included)
        # would hand back a TPQ_DEVICE_MBPS far off the resolve
        # throughput whose error ratio tripped the band in the first place
        dom_bytes = g(dev_routes[dom_route], "bytes_in")
        dev_bps = dom_bytes / dm if dom_bytes and dm else 0.0
        dev_err = round(dm / dp, 3) if dm and dp else None
        out["device"] = {
            "dominant_route": dom_route,
            "dominant_kernel": dom_kernel,
            "measured_seconds": round(dm, 9),
            "predicted_seconds": round(dp, 9),
            "error_ratio": dev_err,
            "measured_device_mbps": (round(dev_bps / 1e6, 1)
                                     if dev_bps else None),
        }
        lo, hi = DOCTOR_ERROR_BAND
        if dev_err is not None and dev_bps and not (lo <= dev_err <= hi):
            from .ship import recalibrate_device_mbps

            out["recalibrate_device_mbps"] = recalibrate_device_mbps(dev_bps)
        # fusion-win: a fused megakernel route whose MEASURED device
        # seconds beat the UNFUSED chain's prediction for the same bytes
        # (ship.ShipPlanner.unfused_device_costs, recorded on the fused
        # ship records).  Reported for the dominant (most bytes_in) fused
        # route; interpret-mode runs never qualify on timing grounds here
        # because their measured seconds are not kernel measurements —
        # the ledger fingerprint's pallas mode says which kind a run was.
        from .ship import FUSED_ROUTES as _FUSED

        fused = sorted((r for r in dev_routes if r in _FUSED),
                       key=lambda r: (-g(dev_routes[r], "bytes_in"), r))
        for r in fused:
            fm = g(dev_routes[r], "device_seconds")
            fp = float((routes_pred.get(r) or {})
                       .get("predicted_unfused_device_s") or 0.0)
            if fm and fp and fm < fp:
                out["fusion_win"] = {
                    "route": r,
                    "measured_seconds": round(fm, 9),
                    "unfused_predicted_seconds": round(fp, 9),
                    "speedup": round(fp / fm, 2),
                }
                break
    cache_sec = tree.get("cache")
    cache_sec = cache_sec if isinstance(cache_sec, dict) else {}
    for tier in ("device", "host"):  # device pressure is the scarcer tier
        tc = cache_sec.get(tier)
        if not isinstance(tc, dict):
            continue
        ev, hits, misses = g(tc, "evictions"), g(tc, "hits"), g(tc, "misses")
        lookups = hits + misses
        rate = hits / lookups if lookups else 0.0
        if (ev >= CACHE_THRASH_MIN_EVICTIONS and lookups
                and rate < CACHE_THRASH_MAX_HIT_RATE):
            # rank the top-evicting file from the per-file map (merged
            # trees sum counts per file, so the ranking stays truthful
            # across merged snapshots)
            files = tc.get("evict_files")
            files = files if isinstance(files, dict) else {}
            top = max(files, key=lambda f: (files[f], f)) if files else None
            out["cache"] = {
                "verdict": "cache-thrash",
                "tier": tier,
                "evictions": int(ev),
                "hit_rate": round(rate, 3),
                "held_bytes": int(g(tc, "held_bytes")),
                "capacity_bytes": int(g(tc, "capacity_bytes")),
                "top_evict_file": top,
                "top_evict_count": int(files.get(top, 0)) if top else 0,
                # the knob that actually governs this tier's budget (the
                # host tier may be riding the plan cache's in fallback)
                "budget_knob": tc.get("budget_knob") or (
                    "TPQ_RESULT_CACHE_HBM_MB" if tier == "device"
                    else "TPQ_RESULT_CACHE_MB"),
            }
            break
    circ = serve.get("circuit")
    circ = circ if isinstance(circ, dict) else {}
    if g(circ, "open_now") > 0:
        # a tripped breaker names its file: the operator's next step is
        # the FILE (quarantine/replace it), not the service's tuning
        out["circuit_open"] = {
            "verdict": "circuit-open",
            "files": [str(f) for f in (circ.get("open_files") or [])],
            "fast_fails": int(g(circ, "fast_fails")),
            "opened": int(g(circ, "opened") + g(circ, "reopened")),
        }
    sheds = _sheds
    if overload_pressure >= OVERLOAD_MIN_REJECTS:
        # the service is turning work away: name WHO is driving the
        # pressure.  Demand (submitted + rejected) ranks the offender —
        # rejected requests never reach `submitted`, so admitted flow
        # alone would hide exactly the tenant being throttled hardest
        tens = {n: t for n, t in (serve.get("tenants") or {}).items()
                if isinstance(t, dict)}
        demand = {n: g(t, "submitted") + g(t, "rejected")
                  for n, t in tens.items()}
        offender = (max(demand, key=lambda n: (demand[n], n))
                    if demand else None)
        victims = sorted(n for n, t in tens.items()
                         if n != offender and g(t, "rejected") > 0)
        hint = g(serve, "retry_after_hint_s")
        out["overload"] = {
            "verdict": "overload",
            "rejected": int(g(serve, "rejected")),
            "sheds": {"low": int(g(sheds, "low")),
                      "normal": int(g(sheds, "normal"))},
            "offending_tenant": offender,
            "offender_demand": int(demand.get(offender, 0)) if offender
            else 0,
            "victims": victims,
            "retry_after_hint_s": round(hint, 3) if hint else None,
            "advice": (
                f"tenant '{offender}' drives the overload: lower its "
                "fair-share weight or give it a dedicated budget slice "
                "(TPQ_SERVE_TENANTS), or raise queue_depth/max_memory"
                if offender else
                "raise queue_depth/max_memory or shed earlier"),
        }
    if slo_burn is not None:
        out["slo_burn"] = slo_burn
    io_sec = tree.get("io")
    io_sec = io_sec if isinstance(io_sec, dict) else {}
    hedges_issued = g(io_sec, "hedges_issued")
    if hedges_issued >= HEDGE_VERDICT_MIN_ISSUED:
        hedges_won = g(io_sec, "hedges_won")
        wasted = g(io_sec, "hedges_wasted_bytes")
        win_rate = hedges_won / hedges_issued
        if win_rate < HEDGE_VERDICT_MIN_WIN_RATE and wasted > 0:
            # duplicates were paid but the primary almost always won the
            # race anyway: the hedge delay is below the real p90 —
            # raise TPQ_IO_HEDGE_MS (or let auto re-learn) before the
            # wasted bytes outweigh the tail they were buying down
            out["hedge"] = {
                "verdict": "hedge-ineffective",
                "issued": int(hedges_issued),
                "won": int(hedges_won),
                "win_rate": round(win_rate, 3),
                "wasted_bytes": int(wasted),
            }
    eng = io_sec.get("engine")
    eng = eng if isinstance(eng, dict) else {}
    eng_done = g(eng, "completed") + g(eng, "failed")
    if eng_done >= IO_CONC_MIN_FETCHES:
        cap = g(eng, "inflight_cap")
        peak = g(eng, "inflight_peak")
        qw = g(eng, "queue_wait_seconds")
        fs = g(eng, "fetch_seconds")
        io_lane = g(pipe, "io_seconds")
        # the io lane must actually dominate the decode-side lanes: a run
        # bottlenecked on decompress or staging has no concurrency story
        io_dominant = (io_lane > 0
                       and io_lane >= (g(pipe, "decompress_seconds")
                                       + g(pipe, "recompress_seconds"))
                       and io_lane >= g(pipe, "stage_seconds"))
        pf = int(g(pipe, "prefetch"))
        if io_dominant and cap > 0:
            knob = None
            if (peak >= IO_CONC_PIN_FRACTION * cap and qw > 0
                    and qw >= IO_CONC_QUEUE_WAIT_RATIO * fs):
                # every slot stayed occupied and ranges queued for slots
                # far longer than they fetched: the engine cap is the wall
                knob = "TPQ_IO_INFLIGHT"
            elif (pf > 0 and peak <= pf + 1
                  and peak < IO_CONC_PIN_FRACTION * cap and fs > 0):
                # slots were free (no slot queueing to speak of) but the
                # feed never got deeper than the decode window: in-flight
                # depth is prefetch-limited, not engine-limited
                knob = "prefetch="
            if knob is not None:
                out["io_concurrency"] = {
                    "verdict": "io-concurrency-bound",
                    "inflight_peak": int(peak),
                    "inflight_cap": int(cap),
                    "queue_wait_seconds": round(qw, 6),
                    "fetch_seconds": round(fs, 6),
                    "knob": knob,
                    "advice": (
                        f"in-flight peak {int(peak)} pinned at the engine "
                        f"cap {int(cap)} with {qw:.3f}s of slot queueing vs "
                        f"{fs:.3f}s fetching: raise TPQ_IO_INFLIGHT"
                        if knob == "TPQ_IO_INFLIGHT" else
                        f"in-flight peak {int(peak)} never left the "
                        f"prefetch={pf} decode window (engine cap "
                        f"{int(cap)} idle): raise prefetch="),
                }
    fb = reader.get("ship_feedback")
    routes = (fb or {}).get("routes") or {}
    if routes:
        pred = sum(float(r.get("predicted_seconds") or 0.0)
                   for r in routes.values())
        timed = [float(r["measured_seconds"]) for r in routes.values()
                 if r.get("measured_seconds") is not None]
        # same null-vs-0.0 contract as ship_feedback: "no route was ever
        # timed" is None, a tiny-but-real sum stays a number (9 decimals,
        # is-not-None gating — truthiness would flatten ~1e-7s to "unmeasured")
        meas = sum(timed) if timed else None
        link_bps = float(fb.get("link_bytes_per_sec") or 0.0)
        err = (round(meas / pred, 3)
               if meas is not None and pred else None)
        out["route_model"] = {
            "predicted_seconds": round(pred, 9),
            "measured_seconds": round(meas, 9) if meas is not None else None,
            "error_ratio": err,
            "measured_link_mbps": (round(link_bps / 1e6, 1)
                                   if link_bps else None),
            "planner_link_mbps": reader.get("planner_link_mbps") or None,
        }
        lo, hi = DOCTOR_ERROR_BAND
        if err is not None and link_bps and not (lo <= err <= hi):
            from .ship import recalibrate_link_mbps

            out["recalibrate_link_mbps"] = recalibrate_link_mbps(link_bps)
    if wr_total > 0:
        # the write-side attribution: same rule shape as the read lanes —
        # the dominant lane names the bottleneck (encode = CPU encoding,
        # compress = the codec, flush = the sink, stall = the memory
        # budget), so a slow write is attributable the way a slow read is
        wd = max(wr_lanes, key=lambda k: (wr_lanes[k], k))
        out["write"] = {
            "lanes": {k: round(v, 6) for k, v in wr_lanes.items()},
            "dominant_lane": wd,
            "verdict": f"write-{wd}-bound",
            "dominant_share": round(wr_lanes[wd] / wr_total, 4),
            "rows_per_sec": wr.get("rows_per_sec") or 0.0,
            "bytes_per_sec": wr.get("bytes_per_sec") or 0.0,
        }
    return out


# ---------------------------------------------------------------------------
# autopsy: rule-based hang/crash attribution (the pq_tool autopsy backend)
# ---------------------------------------------------------------------------

# thread-blockage classes autopsy can assign, most diagnostic first; the
# rule table below walks each dumped stack innermost-out and returns the
# first matching class (obs/threading frames are skipped, not classified —
# a signal handler's own frames sit on top of the interrupted wait)
AUTOPSY_CLASSES = ("io-wait", "budget-wait", "queue-get", "future-wait",
                   "device-sync", "worker-idle", "lock-wait", "obs",
                   "running")


def _classify_frames(frames) -> str:
    """One thread's blockage class from its dumped stack (outermost-first
    frame dicts, as FlightRecorder stores them)."""
    waitish = None
    for f in reversed(frames or []):  # innermost first
        path = str(f.get("file", "")).replace("\\", "/")
        func = str(f.get("func", ""))
        if path.endswith("tpu_parquet/iostore.py"):
            # blocked inside the IO backend (a stalled fetch, an injected
            # stall, a backoff sleep): the network-stall verdict's signal —
            # checked before the generic waits because the stalled worker's
            # INNERMOST frames are an Event/sleep in threading.py
            return "io-wait"
        if path.endswith("tpu_parquet/alloc.py") and func in (
                "acquire", "try_acquire"):
            return "budget-wait"
        if path.endswith("/queue.py") and func in ("get", "put"):
            return "queue-get"
        if "concurrent/futures" in path and func in ("result", "wait"):
            return "future-wait"
        if "concurrent/futures" in path and func == "_worker":
            # a pool worker idle on its (C-level, frame-less) work queue:
            # the producer side waiting for work, NOT a starved consumer —
            # it must never feed the dead-worker verdict
            return "worker-idle"
        if "/jax/" in path or func == "block_until_ready":
            return "device-sync"
        if path.endswith("/threading.py") or path.endswith(
                "tpu_parquet/obs.py"):
            # a bare lock/Event wait, or the recorder's own dump frames on
            # top of the interrupted stack: keep scanning outward for the
            # frame that says WHOSE wait this is
            if func in ("wait", "_wait_for_tstate_lock", "join"):
                waitish = waitish or "lock-wait"
            continue
    return waitish or "running"


# exception class names the data-corruption autopsy rule recognizes: the
# ParquetError family a decode raises for malformed INPUT (HangError /
# RetryExhaustedError are deliberately absent — hangs and transport faults
# have their own verdicts)
_DATA_ERROR_TYPES = frozenset({
    "ParquetError", "DataIntegrityError", "CompressionError", "RLEError",
    "ThriftError", "CheckpointError",
})


def autopsy_dump(doc: dict) -> dict:
    """Attribute a flight-recorder dump: which lane stopped advancing
    first, which threads are blocked on what, the longest budget-wait age,
    and a one-line probable cause (rule-based, golden-tested).

    Raises ``ValueError`` for anything that is not a readable
    ``FLIGHT_VERSION`` dump — autopsy must refuse documents it would
    misread, the same contract as the registry/ledger versions.
    """
    if not isinstance(doc, dict) or "flight_version" not in doc:
        raise ValueError("not a flight-recorder dump (no flight_version)")
    if doc.get("flight_version") != FLIGHT_VERSION:
        raise ValueError(
            f"flight_version {doc.get('flight_version')!r} != "
            f"{FLIGHT_VERSION}")
    wd = doc.get("watchdog") or {}
    threads_out: dict = {}
    classes: dict[str, int] = {}
    for tid, t in (doc.get("threads") or {}).items():
        if not isinstance(t, dict):
            continue
        name = str(t.get("name", "?"))
        if name.startswith(("tpq-watchdog", "tpq-sampler")):
            cls = "obs"
        else:
            cls = _classify_frames(t.get("stack"))
        last = t.get("last_event") or None
        threads_out[tid] = {
            "name": name,
            "alive": bool(t.get("alive", True)),
            "class": cls,
            "last_event": ({"name": last.get("name"),
                            "age_s": last.get("age_s")}
                           if isinstance(last, dict) else None),
        }
        classes[cls] = classes.get(cls, 0) + 1
    budgets = [b for b in (doc.get("budgets") or []) if isinstance(b, dict)]
    waiters = sum(int(b.get("waiters") or 0) for b in budgets)
    longest = max((float(b.get("longest_wait_s") or 0.0) for b in budgets),
                  default=0.0)
    dead = [t["name"] for t in threads_out.values() if not t["alive"]]
    stalled_first = wd.get("stalled_first")
    # quarantine state at dump time (quarantine.Quarantine registers itself
    # as a flight source): recorded data errors + the FIRST bad
    # (file, column, page) — the data-corruption verdict's evidence
    q_first = None
    q_errors = 0
    for label, s in sorted((doc.get("samples") or {}).items()):
        if label.startswith("quarantine") and isinstance(s, dict):
            q_errors += int(s.get("errors") or 0)
            if q_first is None and isinstance(s.get("first"), dict):
                q_first = s["first"]
    err = doc.get("error") or {}
    data_error = (isinstance(err, dict)
                  and err.get("type") in _DATA_ERROR_TYPES)
    # an explicit error of some OTHER class outranks contained quarantine
    # records: errors the run already moved past must not mask the crash
    # that actually killed it
    unrelated_error = (isinstance(err, dict) and err.get("type")
                       and not data_error)
    # the in-flight range of any IO store at dump time (iostore.IOStats
    # registers itself as a flight source) — a stalled fetch's single most
    # diagnostic fact
    io_inflight = None
    for label, s in sorted((doc.get("samples") or {}).items()):
        if (label.startswith("iostore") and isinstance(s, dict)
                and s.get("inflight_age_s")):
            if io_inflight is None or (s["inflight_age_s"]
                                       > io_inflight["age_s"]):
                io_inflight = {"offset": s.get("inflight_offset"),
                               "size": s.get("inflight_size"),
                               "age_s": s.get("inflight_age_s")}
    # the scan service's admission state at dump time (serve.ScanService
    # registers itself as a flight source): the report names the OLDEST
    # in-flight request — for a one-request wedge, that IS the stuck one
    serve_state = None
    sv = (doc.get("samples") or {}).get("serve")
    if isinstance(sv, dict):
        oldest = None
        for rid, r in sorted((sv.get("requests") or {}).items()):
            if isinstance(r, dict) and (
                    oldest is None
                    or float(r.get("age_s") or 0.0)
                    > float(oldest[1].get("age_s") or 0.0)):
                oldest = (rid, r)
        serve_state = {
            "queue_depth": sv.get("queue_depth"),
            "in_flight": sv.get("in_flight"),
            "stuck_request": ({"id": oldest[0],
                               "path": oldest[1].get("path"),
                               "age_s": oldest[1].get("age_s")}
                              if oldest is not None else None),
            # open circuits at dump time (BreakerBoard.open_files shape):
            # the verdict names the first file when nothing more specific
            # explains the dump
            "circuit_open": [c for c in (sv.get("circuit_open") or [])
                             if isinstance(c, dict) and c.get("file")],
        }
    # the rule table, most specific first.  Data corruption never hangs —
    # an explicit data-integrity error (or quarantined failures on a crash
    # dump) outranks every stall inference.
    if data_error or (q_errors and not stalled_first
                      and not unrelated_error):
        verdict = "data-corruption"
        if q_first is not None:
            where = (f" — first bad: file {q_first.get('file')!r}, column "
                     f"{q_first.get('column')!r}, row group "
                     f"{q_first.get('row_group')}, page {q_first.get('page')}")
        elif isinstance(err, dict) and err.get("message"):
            where = f" — {err['message']}"
        else:
            where = ""
        cause = (f"the input data is malformed, not the pipeline"
                 f"{where}; quarantine the named unit "
                 f"(TPQ_ON_DATA_ERROR=skip_unit contains it, "
                 f"pq_tool quarantine summarizes the ledger)")
    elif classes.get("io-wait") or (io_inflight is not None
                                    and wd.get("stalled_first")):
        verdict = "network-stall"
        where = (f" (offset {io_inflight['offset']}, "
                 f"{io_inflight['size']} bytes, in flight "
                 f"{io_inflight['age_s']:g}s)" if io_inflight else "")
        cause = (f"a range fetch stalled in the IO backend{where} — the "
                 f"store never returned and every lane behind it froze; "
                 f"check the transport, or bound the fetch with "
                 f"TPQ_IO_DEADLINE_S so retries can take over")
    elif classes.get("budget-wait") or waiters:
        verdict = "budget-wait"
        cause = (f"submitter starved on InFlightBudget "
                 f"({max(waiters, classes.get('budget-wait', 0))} waiter(s), "
                 f"longest wait {longest:.1f}s): nothing downstream releases "
                 f"bytes — raise max_memory, shrink prefetch, or unblock the "
                 f"consumer")
    elif classes.get("device-sync"):
        verdict = "device-sync"
        cause = ("a thread is blocked inside the device runtime "
                 "(stage/dispatch never returned) — a device hang, not a "
                 "host-side bug")
    elif classes.get("queue-get") or classes.get("future-wait"):
        verdict = "dead-worker"
        cause = ("consumers are waiting on work that is not being produced"
                 + (f" (dead thread(s): {', '.join(sorted(dead))})"
                    if dead else "")
                 + " — a worker died or its input stream stopped")
    elif stalled_first:
        verdict = f"stalled-{stalled_first.split('.', 1)[0]}"
        cause = (f"lane {stalled_first!r} stopped advancing first with no "
                 f"classified blocked thread — likely stuck in user code or "
                 f"a long single unit of work")
    elif serve_state and serve_state.get("circuit_open"):
        # nothing wedged or corrupt, but circuits are open: the dump's
        # most actionable fact is WHICH file keeps failing
        first_open = serve_state["circuit_open"][0]
        verdict = "circuit-open"
        cause = (f"circuit open for {first_open['file']!r} "
                 f"(next probe in {first_open.get('retry_after_s', '?')}s)"
                 f" — the file keeps failing its requests; inspect or "
                 f"replace it (pq_tool quarantine shows contained errors), "
                 f"healthy files are unaffected")
    else:
        verdict = "inconclusive"
        cause = ("no blocked thread classified and no stalled lane recorded"
                 " — re-dump while the process is actually wedged")
    return {
        "reason": doc.get("reason"),
        "pid": doc.get("pid"),
        "stalled_first": stalled_first,
        "ages": wd.get("ages") or {},
        "hang_s": wd.get("hang_s"),
        "threads": threads_out,
        "budget": {"waiters": waiters,
                   "longest_wait_s": round(longest, 3)} if budgets else None,
        "io": io_inflight,
        "serve": serve_state,
        "data_errors": ({"errors": q_errors, "first": q_first}
                        if q_errors or data_error else None),
        "error": doc.get("error"),
        "verdict": verdict,
        "probable_cause": cause,
    }


# ---------------------------------------------------------------------------
# dump triggers: worker crash, unhandled exception, signal
# ---------------------------------------------------------------------------

_crash_dump_done = False


def note_worker_crash(exc: BaseException) -> None:
    """Called by pipeline/loader worker wrappers when ``fn`` raises: the
    crash lands in the ring unconditionally (``worker_crash`` instant);
    with ``TPQ_FLIGHT`` set, the FIRST crash also writes a dump — the
    artifact for a worker death the consumer may never fully report."""
    global _crash_dump_done
    rec = flight_recorder()
    rec.record("i", "worker_crash", time.perf_counter(), 0.0,
               {"type": type(exc).__name__, "msg": str(exc)[:200]})
    from .errors import HangError

    if isinstance(exc, HangError):
        # the watchdog's own abort propagating through a worker: it
        # already wrote the hang dump (mid-stall state, the one autopsy
        # wants) — a second dump here would OVERWRITE it with a
        # post-mortem taken after the stall cleared
        return
    if os.environ.get("TPQ_FLIGHT") and not _crash_dump_done:
        _crash_dump_done = True
        try:
            rec.dump(reason="worker-crash", error=exc)
        except Exception:  # noqa: BLE001 — diagnostics never mask the crash
            pass


_hooks_installed = False
_installed_excepthook = None
_installed_prev_hook = None


def install_flight_hooks(force: bool = False) -> dict:
    """Install the opt-in dump triggers (idempotent; returns what took):

    - ``TPQ_DUMP_SIGNAL=<USR1|SIGUSR1|10|...>``: a signal handler that
      writes a flight dump on receipt (``faulthandler`` style — send the
      signal to a hung process, collect the dump, run ``pq_tool autopsy``).
      Main-thread only; silently skipped elsewhere.
    - ``TPQ_FLIGHT=<path>``: a ``sys.excepthook`` wrapper that writes a
      dump before the interpreter dies of an unhandled exception (the
      exit-on-error artifact), chaining to the previous hook.

    Runs once at import; ``force=True`` re-reads the env (tests)."""
    global _hooks_installed
    out = {"signal": False, "excepthook": False}
    if _hooks_installed and not force:
        return out
    _hooks_installed = True
    sig = os.environ.get("TPQ_DUMP_SIGNAL", "")
    if sig:
        try:
            import signal as _signal

            if sig.isdigit():
                signum = _signal.Signals(int(sig))
            else:
                signum = getattr(
                    _signal, sig if sig.startswith("SIG") else f"SIG{sig}")

            def _dump_async():
                try:
                    flight_recorder().dump(reason="signal")
                except Exception:  # noqa: BLE001 — never crash the helper
                    pass

            def _on_dump_signal(signum, frame):  # noqa: ARG001
                # Python signal handlers run on the MAIN thread between its
                # bytecodes: the interrupted code may hold one of the locks
                # the snapshot needs (recorder ring, PipelineStats, budget
                # cv), and a same-thread re-acquire would deadlock the very
                # process this handler is meant to diagnose.  A helper
                # thread WAITS on those locks instead (they are all short,
                # never-held-while-blocking critical sections).
                try:
                    threading.Thread(target=_dump_async,
                                     name="tpq-flight-dump",
                                     daemon=True).start()
                except Exception:  # noqa: BLE001 — never crash the handler
                    pass

            _signal.signal(signum, _on_dump_signal)
            out["signal"] = True
        except (AttributeError, ValueError, OSError, TypeError):
            pass  # unknown name, non-main thread, unsupported platform
    if os.environ.get("TPQ_FLIGHT"):
        global _installed_excepthook, _installed_prev_hook
        prev_hook = sys.excepthook
        if prev_hook is _installed_excepthook and prev_hook is not None:
            # re-install (force=True): chain to the ORIGINAL hook, never to
            # our own previous wrapper — stacking would dump N times and
            # pin every prior wrapper alive
            prev_hook = _installed_prev_hook

        def _flight_excepthook(tp, val, tb):
            try:
                flight_recorder().dump(reason="crash", error=val)
            except Exception:  # noqa: BLE001
                pass
            prev_hook(tp, val, tb)

        _installed_excepthook = _flight_excepthook
        _installed_prev_hook = prev_hook
        sys.excepthook = _flight_excepthook
        out["excepthook"] = True
    return out


install_flight_hooks()
