"""tpu_parquet.data: the training-input subsystem.

Sits above ``reader``/``device_reader``/``pipeline``/``parallel`` and turns
"a directory of parquet files" into "shuffled, sharded, resumable,
fixed-shape batches for N epochs" — the layer every accelerator input stack
(tf.data, Grain) treats as its own subsystem:

- :mod:`~tpu_parquet.data.sampler` — deterministic shuffle as a pure
  function of (seed, epoch, position): epoch-wise unit permutation plus a
  windowed block shuffle, no dataset materialization;
- :mod:`~tpu_parquet.data.loader` — :class:`DataLoader` epoch iteration over
  host or device batches, prefetch-overlapped decode, LPT per-host sharding,
  pad+mask ragged tails, :class:`LoaderStats` observability;
- :mod:`~tpu_parquet.data.checkpoint` — the small versioned state blob
  behind ``loader.state()`` / ``loader.restore(state)``; save → restore →
  iterate is bit-identical to uninterrupted iteration.
"""

from .checkpoint import STATE_VERSION, pack_state, unpack_state
from .loader import DataLoader, LoaderStats
from .sampler import EpochPlan, block_permutation, epoch_unit_order, plan_epoch

__all__ = [
    "DataLoader",
    "LoaderStats",
    "STATE_VERSION",
    "pack_state",
    "unpack_state",
    "EpochPlan",
    "block_permutation",
    "epoch_unit_order",
    "plan_epoch",
]
