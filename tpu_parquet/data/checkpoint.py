"""Versioned, validated checkpoint state for :class:`~tpu_parquet.data.DataLoader`.

The whole point of a deterministic input pipeline is that its position is
SMALL: because the shuffled order is a pure function of (seed, epoch, cursor)
— see data/sampler.py — the checkpoint carries only those scalars plus a
dataset fingerprint, never buffered rows or RNG internals.  Save → restore →
iterate is bit-identical to uninterrupted iteration at any batch boundary,
for any prefetch depth.

Blob layout: ``b"TPQL" | version:u16be | json(state)``.  Every decode error,
type/range violation, unknown version, or fingerprint mismatch raises
:class:`tpu_parquet.errors.CheckpointError` — a checkpoint that cannot be
adopted exactly must fail loudly, never silently mis-seek (the
``loader_state`` fuzz target holds this surface to the same
raise-or-return contract as the file parsers).
"""

from __future__ import annotations

import json

from ..errors import CheckpointError

__all__ = ["STATE_VERSION", "MAGIC", "pack_state", "unpack_state",
           "validate_state", "check_compatible"]

STATE_VERSION = 1
MAGIC = b"TPQL"

# (key, lo, hi) for every required integer field; bounds are sanity rails so
# a mutated blob cannot smuggle astronomically large ints into index math
_INT_FIELDS = (
    # exact-version check lives HERE so dict states (restore(dict)) are held
    # to it too, not only packed blobs
    ("version", STATE_VERSION, STATE_VERSION + 1),
    ("seed", 0, 1 << 64),
    ("epoch", 0, 1 << 62),
    ("rows_taken", 0, 1 << 62),
    ("batch_size", 1, 1 << 40),
    ("shuffle_window", 1, 1 << 40),
    ("n_units", 1, 1 << 40),
    ("total_rows", 0, 1 << 62),
    ("shard_rows", 0, 1 << 62),
)
_BOOL_FIELDS = ("shuffle", "drop_remainder")

# the config half of the state: must match the restoring loader exactly (the
# cursor half — seed/epoch/rows_taken — is what restore ADOPTS).
# dataset_digest hashes the ordered per-unit (rows, bytes, offset) sequence,
# so a reordered or substituted file set with coincidentally matching counts
# still refuses.
_FINGERPRINT = ("batch_size", "shuffle", "shuffle_window", "drop_remainder",
                "shard", "n_units", "total_rows", "shard_rows",
                "dataset_digest")


def _int_field(state: dict, key: str, lo: int, hi: int) -> int:
    v = state.get(key)
    if type(v) is not int:  # bool is an int subclass: excluded on purpose
        raise CheckpointError(
            f"loader state field {key!r} must be an int, got {type(v).__name__}"
        )
    if not lo <= v < hi:
        raise CheckpointError(
            f"loader state field {key!r} = {v} outside [{lo}, {hi})"
        )
    return v


def validate_state(state) -> dict:
    """Strict structural validation; returns ``state`` or raises."""
    if not isinstance(state, dict):
        raise CheckpointError(
            f"loader state must be a dict, got {type(state).__name__}"
        )
    for key, lo, hi in _INT_FIELDS:
        _int_field(state, key, lo, hi)
    for key in _BOOL_FIELDS:
        if type(state.get(key)) is not bool:
            raise CheckpointError(f"loader state field {key!r} must be a bool")
    shard = state.get("shard")
    if (not isinstance(shard, (list, tuple)) or len(shard) != 2
            or any(type(x) is not int for x in shard)):
        raise CheckpointError("loader state field 'shard' must be [index, n]")
    i, n = shard
    if not (1 <= n < 1 << 32 and 0 <= i < n):
        raise CheckpointError(f"loader state shard {i} of {n} out of range")
    if state["rows_taken"] > state["shard_rows"]:
        raise CheckpointError(
            f"loader state cursor {state['rows_taken']} past the shard's "
            f"{state['shard_rows']} rows"
        )
    # quarantine skips (round 13, optional — pre-round-13 blobs carry
    # none): a sorted duplicate-free unit list, its row total, and the
    # skip_file-marked file ordinals.  Structural rails only; the
    # restoring loader cross-checks membership and the row sum.
    skipped = state.get("skipped_units", [])
    if not isinstance(skipped, list) or any(
            type(u) is not int or not 0 <= u < state["n_units"]
            for u in skipped):
        raise CheckpointError(
            "loader state field 'skipped_units' must be a list of unit "
            "ordinals in [0, n_units)")
    if sorted(set(skipped)) != skipped:
        raise CheckpointError(
            "loader state field 'skipped_units' must be sorted and "
            "duplicate-free")
    sr = state.get("skipped_rows", 0)
    if type(sr) is not int or not 0 <= sr <= state["shard_rows"]:
        raise CheckpointError(
            "loader state field 'skipped_rows' out of [0, shard_rows]")
    sf = state.get("skipped_files", [])
    if not isinstance(sf, list) or any(
            type(f) is not int or not 0 <= f < 1 << 32 for f in sf):
        raise CheckpointError(
            "loader state field 'skipped_files' must be a list of file "
            "ordinals")
    if sorted(set(sf)) != sf:
        raise CheckpointError(
            "loader state field 'skipped_files' must be sorted and "
            "duplicate-free")
    # state() only ever emits batch boundaries (k * batch_size) or the
    # epoch-tail cursor (the shard's rows MINUS the quarantined units');
    # anything else is a tampered blob whose adoption would shift every
    # subsequent batch by a fraction of a batch
    rt = state["rows_taken"]
    if (rt % state["batch_size"] != 0
            and rt != state["shard_rows"] - sr):
        raise CheckpointError(
            f"loader state cursor {rt} is not a batch boundary "
            f"(batch_size {state['batch_size']})"
        )
    if state["shard_rows"] > state["total_rows"]:
        raise CheckpointError("loader state shard_rows exceeds total_rows")
    dg = state.get("dataset_digest")
    if type(dg) is not str or not (8 <= len(dg) <= 64):
        raise CheckpointError(
            "loader state field 'dataset_digest' must be a short hex string"
        )
    return state


def pack_state(state: dict) -> bytes:
    """Serialize a validated state dict to the versioned blob."""
    validate_state(state)
    payload = json.dumps(state, sort_keys=True,
                         separators=(",", ":")).encode("utf-8")
    return MAGIC + int(state["version"]).to_bytes(2, "big") + payload


def unpack_state(blob) -> dict:
    """Parse + validate a state blob; raises CheckpointError on anything off."""
    if isinstance(blob, dict):  # already-unpacked states pass through validated
        return validate_state(blob)
    if not isinstance(blob, (bytes, bytearray, memoryview)):
        raise CheckpointError(
            f"loader state blob must be bytes, got {type(blob).__name__}"
        )
    blob = bytes(blob)
    if len(blob) < len(MAGIC) + 2 or blob[: len(MAGIC)] != MAGIC:
        raise CheckpointError("not a loader state blob (bad magic)")
    version = int.from_bytes(blob[len(MAGIC) : len(MAGIC) + 2], "big")
    if version != STATE_VERSION:
        raise CheckpointError(
            f"unsupported loader state version {version} "
            f"(this build reads {STATE_VERSION})"
        )
    try:
        state = json.loads(blob[len(MAGIC) + 2 :].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise CheckpointError(f"corrupt loader state payload: {e}") from e
    state = validate_state(state)
    if state["version"] != version:
        raise CheckpointError("loader state version header/payload mismatch")
    return state


def check_compatible(state: dict, expected: dict) -> None:
    """Refuse a state whose config fingerprint differs from the loader's.

    ``expected`` maps the _FINGERPRINT keys to the restoring loader's values;
    any mismatch means the state describes a DIFFERENT pipeline (other
    dataset, other sharding, other batch geometry) and adopting its cursor
    would silently yield wrong rows.
    """
    for key in _FINGERPRINT:
        got, want = state.get(key), expected[key]
        if key == "shard":
            got, want = list(got), list(want)
        if got != want:
            raise CheckpointError(
                f"loader state mismatch on {key!r}: state has {got!r}, "
                f"this loader has {want!r}"
            )
