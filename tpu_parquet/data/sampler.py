"""Deterministic shuffle planning for the training-input loader.

Every serious accelerator input stack (tf.data, Grain) defines its shuffle as
a *pure function of (seed, epoch, position)* rather than as mutable RNG state
threaded through the pipeline — that is what makes the order reproducible
bit-for-bit across runs, across prefetch depths (prefetch only reorders WORK,
never OUTPUT — pipeline.prefetch_map is an ordered map), and across
save/restore at arbitrary cursors.  This module is that pure function,
factored into two composable stages over metadata only (no row data is ever
materialized to shuffle it):

- **epoch unit permutation** (:func:`epoch_unit_order`): a seeded permutation
  of the shard's (file, row_group) units, fresh per epoch — the global
  component of the shuffle, at the granularity the IO path can actually
  randomize without rereading bytes.
- **window (block) shuffle** (:func:`block_permutation`): the decoded row
  stream is cut into consecutive ``shuffle_window``-row blocks and each block
  is permuted with its own seeded permutation — the local component, bounding
  shuffle memory to one window while decorrelating rows within and across
  unit boundaries.  Keyed by (seed, epoch, shard, block), so any block can be
  reconstructed in isolation: restore decodes only the units the current
  block overlaps.

Randomness comes from numpy's Philox bit generator (a counter-based,
algorithm-pinned stream) keyed through a splitmix64 hash of the id tuple;
permutations are realized as a stable argsort of raw 64-bit draws, so they
depend only on the pinned bit stream — not on ``Generator.permutation``'s
(potentially version-drifting) internals.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = [
    "epoch_unit_order",
    "block_permutation",
    "plan_epoch",
    "EpochPlan",
]

_M64 = (1 << 64) - 1


def _mix64(x: int) -> int:
    """splitmix64 finalizer: a full-avalanche 64-bit hash step."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def _philox(seed: int, *stream: int) -> np.random.Generator:
    """A Philox generator keyed by hash-chaining (seed, *stream) — distinct
    id tuples get statistically independent, reproducible streams."""
    h = _mix64((int(seed) & _M64) ^ 0x5851F42D4C957F2D)
    for s in stream:
        h = _mix64(h ^ _mix64(int(s) & _M64))
    key = np.array([h, _mix64(h ^ 0xDA942042E4DD58B5)], dtype=np.uint64)
    return np.random.Generator(np.random.Philox(key=key))


def _draw_permutation(g: np.random.Generator, n: int) -> np.ndarray:
    """Permutation of range(n) as an argsort of raw 64-bit draws.

    The low ⌈log2 n⌉ bits of each key are overwritten with the element's own
    index, making keys UNIQUE by construction — so the argsort result is
    independent of the sort algorithm (no tie-break to pin down), and the
    default introsort can be used (~4x the stable merge sort on this
    shape, 0.65s → 0.15s of an epoch's consumer time at window=64Ki).
    The index stamp biases only bits that random high bits already dominate.
    """
    if n <= 1:
        return np.arange(n, dtype=np.int64)
    keys = g.integers(0, 1 << 64, size=n, dtype=np.uint64)
    bits = np.uint64(max(int(n - 1).bit_length(), 1))
    keys = (keys >> bits << bits) | np.arange(n, dtype=np.uint64)
    return np.argsort(keys).astype(np.int64)


def epoch_unit_order(seed: int, epoch: int, shard_index: int,
                     n_units: int) -> np.ndarray:
    """The epoch's permutation over a shard's unit list (stream id 1)."""
    return _draw_permutation(_philox(seed, 1, epoch, shard_index), n_units)


def block_permutation(seed: int, epoch: int, shard_index: int,
                      block_index: int, n_rows: int) -> np.ndarray:
    """The in-window row permutation for one shuffle block (stream id 2).

    Self-contained per (seed, epoch, shard, block): a resumed loader
    reconstructs exactly the block its cursor sits in, nothing earlier.
    """
    return _draw_permutation(
        _philox(seed, 2, epoch, shard_index, block_index), n_rows
    )


@dataclass(frozen=True)
class EpochPlan:
    """One shard-epoch's decode order, derived from footers alone.

    ``order`` permutes the shard's local unit ordinals; ``unit_rows`` and the
    cumulative ``starts`` are in PERMUTED order, so a row cursor maps to a
    (unit ordinal, row-within-unit) pair with one searchsorted — the whole
    restore path is this index math plus decoding the units it names.
    """

    epoch: int
    order: np.ndarray       # int64[n]: permuted shard-local unit ordinals
    unit_rows: np.ndarray   # int64[n]: rows per unit, permuted order
    starts: np.ndarray      # int64[n+1]: cumulative rows, permuted order

    @property
    def total_rows(self) -> int:
        return int(self.starts[-1])

    def locate(self, row: int) -> tuple[int, int]:
        """(permuted unit ordinal, row offset within it) holding ``row``.

        Zero-row units never claim a position: searchsorted('right') lands on
        the last unit whose start is ≤ row, then empty units are stepped past
        (their start equals their end, so they can alias the boundary).
        """
        if not 0 <= row < self.total_rows:
            raise IndexError(f"row {row} of {self.total_rows}")
        k = int(np.searchsorted(self.starts, row, side="right")) - 1
        while self.unit_rows[k] == 0:  # boundary-aliased empty unit
            k += 1
        return k, row - int(self.starts[k])


def plan_epoch(seed: int, epoch: int, shard_index: int,
               unit_rows, shuffle: bool) -> EpochPlan:
    """Build the shard-epoch plan over ``unit_rows`` (shard-local order)."""
    rows = np.asarray(unit_rows, dtype=np.int64)
    order = (epoch_unit_order(seed, epoch, shard_index, len(rows))
             if shuffle else np.arange(len(rows), dtype=np.int64))
    permuted = rows[order]
    starts = np.zeros(len(rows) + 1, dtype=np.int64)
    np.cumsum(permuted, out=starts[1:])
    return EpochPlan(epoch=int(epoch), order=order, unit_rows=permuted,
                     starts=starts)
