"""DataLoader: checkpointable, deterministically-shuffled training input.

The layer between "parquet reader" and "training data service" (tf.data /
Grain shaped): iterate a directory of parquet files as shuffled, sharded,
resumable fixed-shape batches, epoch after epoch, with the decode overlapped
behind the consumer by the PR-1 prefetch pipeline.

Structure (all order decisions live in data/sampler.py as pure functions of
(seed, epoch, position) — nothing here owns mutable RNG state):

- the dataset is a list of **(file, row_group) units** read once from the
  footers; per-host sharding assigns units with ``parallel.plan_shards``
  (byte-balanced LPT, identical on every host from the shared footers — no
  coordination traffic, same plan every epoch so shard-union == dataset);
- each epoch permutes the shard's units (global shuffle component) and
  window-shuffles the decoded row stream in ``shuffle_window``-row blocks
  (local component); each unit decodes through the reader's ``prefetch``-deep
  chunk pipeline with one unit of lookahead on
  :func:`~tpu_parquet.pipeline.prefetch_map` — ORDERED overlap, so the
  emitted order is bit-identical at every prefetch depth;
- the cursor is a single row offset into the epoch's shuffled stream:
  ``state()``/``restore()`` (data/checkpoint.py) carry (seed, epoch, cursor)
  plus a config fingerprint, and restore re-decodes only the units the
  cursor's shuffle block overlaps.

Columns must be flat (no repetition) fixed-width null-free — the same
contract as ``DeviceFileReader.iter_batches``, because a training batch needs
a static shape.  The ragged TAIL of an epoch is handled by pad+mask: the last
short batch is zero-padded to ``batch_size`` and carries a boolean mask row
validity column (``drop_remainder=True`` drops it instead, tf.data style).
"""

from __future__ import annotations

import os
import time
from typing import Iterable, Optional, Sequence, Union

import numpy as np

from ..alloc import InFlightBudget
from ..column import ByteArrayData
from ..errors import CheckpointError, ParquetError
from ..footer import read_file_metadata
from ..format import Type
from ..pipeline import PipelineStats, prefetch_map
from ..schema.core import Schema
from . import checkpoint as _ck
from .sampler import block_permutation, plan_epoch

__all__ = ["DataLoader", "LoaderStats", "pad_and_mask", "ship_to_device"]

# the batch contract needs a static row shape: fixed-width physical types
# only (ragged byte arrays and repeated columns have none)
_FIXED_TYPES = (Type.INT32, Type.INT64, Type.FLOAT, Type.DOUBLE, Type.BOOLEAN)


def pad_and_mask(cols: dict, n: int, batch_size: int,
                 mask_key: "str | None" = "mask") -> dict:
    """THE fixed-shape batch contract, shared by :class:`DataLoader` and
    the serve tier's streaming sessions: ``n`` valid rows zero-padded to
    ``batch_size`` plus a boolean row-validity column under ``mask_key``
    (None skips the mask — the loader's ``drop_remainder`` shape).
    Object-dtype columns (streamed byte arrays) pad with ``b""`` — a
    zero there would change the column's value type."""
    batch = {}
    for c, a in cols.items():
        if n < batch_size:
            if a.dtype == object:
                pad = np.empty((batch_size - n,), dtype=object)
                pad[:] = b""
            else:
                pad = np.zeros((batch_size - n,) + a.shape[1:],
                               dtype=a.dtype)
            a = np.concatenate([a, pad])
        batch[c] = a
    if mask_key is not None:
        m = np.zeros(batch_size, dtype=bool)
        m[:n] = True
        batch[mask_key] = m
    return batch


def ship_to_device(batch: dict) -> dict:
    """Stage one host batch onto the accelerator, preserving 64-bit lanes.

    64-bit staging is scoped to the call (never the global flag):
    int64/float64 batches keep their width on device while co-resident
    training code keeps its own dtype semantics."""
    import jax.numpy as jnp

    from ..jax_kernels import enable_x64

    with enable_x64():
        return {c: jnp.asarray(v) for c, v in batch.items()}


class LoaderStats:
    """Loader observability, layered on the decode pipeline's PipelineStats.

    ``decode_wait_seconds`` is consumer time blocked on the decode stream —
    the whole decode cost at ``prefetch=0``, shrinking toward zero as the
    prefetch pool hides it.  ``window_peak_rows`` is the shuffle-window
    high-water mark (buffered rows awaiting a full block).  ``pipeline`` is
    the underlying :class:`~tpu_parquet.pipeline.PipelineStats` (decompress
    time on the worker pool, budget stalls, in-flight peak).
    """

    def __init__(self, pipeline: PipelineStats):
        self.pipeline = pipeline
        self.batches = 0
        self.rows = 0
        self.epochs_completed = 0
        self.padded_batches = 0
        self.decode_wait_seconds = 0.0
        self.window_peak_rows = 0
        self.wall_seconds = 0.0
        # data-error containment accounting (quarantine.py): quarantined
        # failures and the units/rows the skip policy dropped for them
        self.data_errors = 0
        self.units_skipped = 0
        self.rows_skipped = 0
        self._t0: Optional[float] = None

    def touch_wall(self) -> None:
        now = time.perf_counter()
        if self._t0 is None:
            self._t0 = now
        self.wall_seconds = now - self._t0

    @property
    def rows_per_sec(self) -> float:
        return self.rows / self.wall_seconds if self.wall_seconds else 0.0

    @property
    def batches_per_sec(self) -> float:
        return self.batches / self.wall_seconds if self.wall_seconds else 0.0

    def as_dict(self) -> dict:
        return {
            "batches": self.batches,
            "rows": self.rows,
            "epochs_completed": self.epochs_completed,
            "padded_batches": self.padded_batches,
            "wall_seconds": round(self.wall_seconds, 6),
            "decode_wait_seconds": round(self.decode_wait_seconds, 6),
            "window_peak_rows": self.window_peak_rows,
            "data_errors": self.data_errors,
            "units_skipped": self.units_skipped,
            "rows_skipped": self.rows_skipped,
            "rows_per_sec": round(self.rows_per_sec, 1),
            "batches_per_sec": round(self.batches_per_sec, 3),
            "pipeline": self.pipeline.as_dict(),
        }


def _as_dotted(col: Union[str, Sequence[str]]) -> str:
    return col if isinstance(col, str) else ".".join(col)


class _UnitSkipped:
    """In-band marker for a quarantined unit riding the ordered decode
    stream (a worker raise would kill the epoch's prefetch pool).  Carries
    the annotated exception; the consumer notes the quarantine record —
    once, in stream order — so the ledger and the skip set are identical
    at every prefetch depth."""

    __slots__ = ("unit", "exc")

    def __init__(self, unit: int, exc: BaseException):
        self.unit = unit
        self.exc = exc


class DataLoader:
    """Epoch iterator over parquet files as fixed-shape training batches.

    ``for batch in loader`` yields the CURRENT epoch from the current cursor
    (dicts of numpy arrays, or jax arrays with ``to_device=True``), then
    advances to the next epoch — so ``loader.epochs(n)`` chains n epochs and
    a restored loader resumes mid-epoch transparently.

    - ``shard=(i, n)``: decode only shard i of an n-way byte-balanced LPT
      split of the row groups (``parallel.plan_shards``); every host computes
      the identical plan from the footers.  Compose with ``jax.distributed``
      via ``shard=parallel.process_shard()``.
    - ``shuffle=True``: seeded epoch-wise unit permutation + windowed row
      shuffle (see data/sampler.py).  Bit-identical across runs and across
      ``prefetch`` values.
    - ``prefetch=K``: each unit decodes through the PR-1 chunk pipeline
      (its chunks' IO + decompress + decode K-deep on a bounded pool) with
      one unit of lookahead ahead of the shuffle window; ``max_memory``
      bounds cross-unit in-flight bytes with backpressure.
    - ``state()`` / ``restore(state)``: resumable at any batch boundary,
      bit-identically (data/checkpoint.py).
    """

    def __init__(
        self,
        files: Union[str, os.PathLike, Iterable[Union[str, os.PathLike]]],
        batch_size: int,
        *,
        columns: Optional[Iterable[Union[str, Sequence[str]]]] = None,
        shuffle: bool = True,
        seed: int = 0,
        shard: tuple[int, int] = (0, 1),
        drop_remainder: bool = False,
        shuffle_window: int = 4096,
        prefetch: int = 0,
        to_device: bool = False,
        mask_key: str = "mask",
        max_memory: int = 0,
        validate_crc=None,
        trace=None,
        sample_ms=None,
        hang_s=None,
        hang_policy=None,
        on_data_error=None,
        quarantine=None,
    ):
        from ..obs import (register_flight_registry, resolve_hang_s,
                           resolve_sample_ms, resolve_tracer)
        from ..quarantine import Quarantine, resolve_validate

        # span tracer (obs.py): batch/decode-wait spans + window-occupancy
        # counters; None = the TPQ_TRACE process tracer (no-op without the
        # env), a path = per-loader tracer written (with the registry
        # embedded) every time an epoch iterator finishes or is abandoned —
        # the loader has no close(), so iteration end is its close
        self._tracer, self._owns_tracer = resolve_tracer(trace)
        # counter-sampling cadence (obs.Sampler): each __iter__ runs one
        # sampler for the epoch — throughput/queue-depth curves on the trace
        self._sample_ms = resolve_sample_ms(sample_ms)
        # hang watchdog deadline (obs.Watchdog, TPQ_HANG_S / hang_s=): each
        # __iter__ arms one watchdog for the epoch, watching batch/row
        # progress and the decode pipeline's lanes; on a wedge it dumps the
        # flight recorder and (policy "raise") aborts the unit budget so
        # the submitter raises errors.HangError
        self._hang_s = resolve_hang_s(hang_s)
        self._hang_policy = hang_policy
        self._watchdog = None
        self._budget = None  # the live epoch budget (_blocks sets it)
        register_flight_registry(self, "obs_registry")
        # a manifest path (or a directory holding tpq_manifest.json, the
        # sharded writer's multi-file layout) expands to its member list —
        # one dataset handle however many files the writer cut
        from ..write.manifest import expand_dataset

        self._paths, _manifest = expand_dataset(files)
        if not self._paths:
            raise ValueError("DataLoader needs at least one file")
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        if shuffle_window <= 0:
            raise ValueError(
                f"shuffle_window must be positive, got {shuffle_window}")
        si, sn = int(shard[0]), int(shard[1])
        if not (sn >= 1 and 0 <= si < sn):
            raise ValueError(f"shard {shard} out of range")
        self._batch_size = int(batch_size)
        self._shuffle = bool(shuffle)
        self._seed = int(seed) & ((1 << 64) - 1)
        self._shard = (si, sn)
        self._drop_remainder = bool(drop_remainder)
        self._shuffle_window = int(shuffle_window)
        self._prefetch = int(prefetch)
        self._to_device = bool(to_device)
        self._mask_key = mask_key
        self._max_memory = int(max_memory)
        self._validate_crc = resolve_validate(validate_crc)
        # data-error containment (quarantine.py, TPQ_ON_DATA_ERROR):
        # under skip_unit/skip_file a corrupt unit is quarantined and
        # DROPPED from the epoch stream deterministically — the skip set
        # rides the checkpoint blob so save→restore→iterate replays the
        # identical batch stream, skips included
        self._quarantine = (quarantine if quarantine is not None
                            else Quarantine(on_data_error))
        # ONE inert raise-policy engine shared by every per-unit inner
        # reader (a fresh engine per unit would re-parse the env and take
        # the flight-registry lock thousands of times per epoch)
        self._inner_quarantine = Quarantine("raise")
        self._skipped_units: set[int] = set()  # this epoch's quarantined units
        self._columns = (None if columns is None
                         else [_as_dotted(c) for c in columns])

        # -- dataset inventory: footers only, no data bytes -------------------
        self._metas = []
        self._unit_map: list[tuple[int, int]] = []  # unit -> (file, row group)
        unit_rows, unit_sizes, unit_costs = [], [], []
        for fi, path in enumerate(self._paths):
            with open(path, "rb") as f:
                md = read_file_metadata(f)
            self._metas.append(md)
            for gi, rg in enumerate(md.row_groups):
                self._unit_map.append((fi, gi))
                unit_rows.append(int(rg.num_rows or 0))
                comp = sum(cc.meta_data.total_compressed_size or 0
                           for cc in (rg.columns or [])
                           if cc.meta_data is not None)
                unc = sum(cc.meta_data.total_uncompressed_size or 0
                          for cc in (rg.columns or [])
                          if cc.meta_data is not None)
                unit_sizes.append(comp)
                unit_costs.append(comp + max(unc, comp))
        if not self._unit_map:
            raise ParquetError("DataLoader: no row groups in the file set")
        self._unit_rows_all = unit_rows
        self._unit_cost_all = unit_costs
        # dataset identity for the checkpoint fingerprint: the ordered
        # per-unit (rows, compressed bytes, first byte offset) sequence —
        # path-independent (the same files restore from any mount point),
        # but a reordered/substituted file set changes it, so a stale blob
        # refuses instead of silently yielding wrong rows
        import hashlib

        h = hashlib.sha256()
        for (fi, gi), r, s in zip(self._unit_map, unit_rows, unit_sizes):
            rg = self._metas[fi].row_groups[gi]
            off = min((cc.meta_data.data_page_offset or 0
                       for cc in (rg.columns or [])
                       if cc.meta_data is not None), default=0)
            h.update(f"{r},{s},{off};".encode())
        self._dataset_digest = h.hexdigest()[:16]
        self._colnames = self._check_schemas()
        if (not self._drop_remainder and self._mask_key is not None
                and self._mask_key in self._colnames):
            raise ValueError(
                f"mask_key {self._mask_key!r} collides with a selected "
                f"column; pass a different mask_key (or None)"
            )

        # -- per-host sharding: identical byte-balanced plan on every host ----
        from ..parallel import plan_shards  # deferred: parallel imports jax

        plan = plan_shards(unit_sizes, sn)
        self._my_units = plan[si]  # global unit ids, ascending
        self._shard_unit_rows = np.array(
            [unit_rows[u] for u in self._my_units], dtype=np.int64)
        self._shard_rows = int(self._shard_unit_rows.sum())
        self._total_rows = int(sum(unit_rows))

        # -- cursor + stats ---------------------------------------------------
        self._epoch = 0
        self._rows_taken = 0
        self._bad_files: set[int] = set()  # skip_file marks, this epoch
        self._pstats = PipelineStats(prefetch=self._prefetch,
                                     budget_bytes=self._max_memory,
                                     tracer=self._tracer)
        self._stats = LoaderStats(self._pstats)

    # -- schema validation ----------------------------------------------------

    def _check_schemas(self) -> list[str]:
        """Selected columns exist in EVERY file, flat and fixed-width, with
        matching physical types; returns their dotted names (file-0 order)."""
        names = None
        types = {}
        for fi, md in enumerate(self._metas):
            schema = Schema.from_file_metadata(md)
            if self._columns is not None:
                paths = [tuple(c.split(".")) for c in self._columns]
                if not schema.selection_matches(paths):
                    known = [".".join(l.path) for l in schema.leaves]
                    raise ParquetError(
                        f"columns {self._columns} match no columns of "
                        f"{self._paths[fi]}; available: {known}"
                    )
                schema.set_selected(paths)
            leaves = schema.selected_leaves()
            here = [".".join(l.path) for l in leaves]
            # column-SET mismatch first: a later file with an extra column
            # must say so, not fall through to a bogus changed-type error
            if names is None:
                names = here
            elif set(names) != set(here):
                raise ParquetError(
                    f"file {self._paths[fi]} has columns {sorted(here)}, "
                    f"expected {sorted(names)}"
                )
            for leaf, name in zip(leaves, here):
                if leaf.max_rep > 0:
                    raise TypeError(
                        f"DataLoader needs flat columns; {name!r} is repeated"
                    )
                if leaf.physical_type not in _FIXED_TYPES:
                    raise TypeError(
                        f"DataLoader needs fixed-width columns; {name!r} is "
                        f"{leaf.physical_type!r} (project it out with "
                        f"columns=[...])"
                    )
                if fi == 0:
                    types[name] = leaf.physical_type
                elif types[name] != leaf.physical_type:
                    raise ParquetError(
                        f"column {name!r} changes physical type across files"
                    )
        return names

    # -- inventory accessors ---------------------------------------------------

    @property
    def batch_size(self) -> int:
        return self._batch_size

    @property
    def column_names(self) -> list[str]:
        return list(self._colnames)

    @property
    def num_rows(self) -> int:
        """Rows this shard yields per epoch (before drop_remainder)."""
        return self._shard_rows

    @property
    def num_batches(self) -> int:
        """Batches per epoch for this shard."""
        full, rem = divmod(self._shard_rows, self._batch_size)
        return full + (1 if rem and not self._drop_remainder else 0)

    @property
    def epoch(self) -> int:
        return self._epoch

    def stats(self) -> LoaderStats:
        return self._stats

    def obs_registry(self):
        """This loader's unified metrics tree (obs.StatsRegistry): loader
        counters + the decode pipeline's per-stage sums and histograms."""
        from ..obs import StatsRegistry

        reg = StatsRegistry()
        reg.add_loader(self._stats)
        if (len(self._quarantine.log)
                or self._quarantine.units_skipped):
            reg.add_data_errors(self._quarantine)
        return reg

    # -- checkpoint ------------------------------------------------------------

    def state(self) -> dict:
        """The loader's position as a small JSON-safe dict (see
        data/checkpoint.py for the versioned blob form)."""
        return {
            "version": _ck.STATE_VERSION,
            "seed": self._seed,
            "epoch": self._epoch,
            "rows_taken": self._rows_taken,
            "batch_size": self._batch_size,
            "shuffle": self._shuffle,
            "shuffle_window": self._shuffle_window,
            "drop_remainder": self._drop_remainder,
            "shard": list(self._shard),
            "n_units": len(self._unit_map),
            "total_rows": self._total_rows,
            "shard_rows": self._shard_rows,
            "dataset_digest": self._dataset_digest,
            # the CURRENT epoch's quarantine skips: restore replays them
            # proactively, so the resumed batch stream is bit-identical to
            # the uninterrupted one — skips included (quarantine.py)
            "skipped_units": sorted(self._skipped_units),
            "skipped_rows": sum(int(self._unit_rows_all[u])
                                for u in self._skipped_units),
            "skipped_files": sorted(self._bad_files),
        }

    def state_blob(self) -> bytes:
        return _ck.pack_state(self.state())

    def restore(self, state) -> "DataLoader":
        """Adopt a saved cursor (dict or packed blob); returns self.

        Raises :class:`~tpu_parquet.errors.CheckpointError` unless the
        state's config fingerprint matches this loader exactly — a cursor
        into a different dataset/sharding/batch geometry must never be
        adopted silently.
        """
        st = _ck.unpack_state(state)
        own = self.state()
        _ck.check_compatible(st, {k: own[k] for k in
                                  ("batch_size", "shuffle", "shuffle_window",
                                   "drop_remainder", "shard", "n_units",
                                   "total_rows", "shard_rows",
                                   "dataset_digest")})
        # quarantine skips (absent in pre-round-13 blobs: no skips then).
        # Cross-checks beyond validate_state's structural ones: the units
        # must belong to THIS shard and their rows must sum to the blob's
        # skipped_rows — a tampered skip set must never silently mis-seek.
        skipped = st.get("skipped_units", [])
        mine = set(int(u) for u in self._my_units)
        bad = [u for u in skipped if u not in mine]
        if bad:
            raise CheckpointError(
                f"loader state skipped_units {bad[:8]} not in this "
                f"loader's shard")
        rows = sum(int(self._unit_rows_all[u]) for u in skipped)
        if rows != st.get("skipped_rows", 0):
            raise CheckpointError(
                f"loader state skipped_rows {st.get('skipped_rows', 0)} "
                f"does not match the named units' {rows} rows")
        n_files = len(self._paths)
        bad_files = [f for f in st.get("skipped_files", [])
                     if not 0 <= f < n_files]
        if bad_files:
            raise CheckpointError(
                f"loader state skipped_files {bad_files[:8]} out of range "
                f"({n_files} files)")
        self._seed = st["seed"]
        self._epoch = st["epoch"]
        self._rows_taken = st["rows_taken"]
        self._skipped_units = set(int(u) for u in skipped)
        self._bad_files = set(int(f) for f in st.get("skipped_files", []))
        return self

    # -- decode ----------------------------------------------------------------

    def _note_unit_skip(self, unit: int) -> None:
        """Account one quarantined/dropped unit (idempotent): the skip set
        (checkpointed), LoaderStats, the engine's counters, and a
        flight-recorder instant naming the unit."""
        if unit in self._skipped_units:
            return
        self._skipped_units.add(unit)
        rows = int(self._unit_rows_all[unit])
        self._stats.units_skipped += 1
        self._stats.rows_skipped += rows
        self._quarantine.note_unit_skipped(rows)
        tr = self._tracer
        if tr.active:
            fi, gi = self._unit_map[unit]
            tr.instant("unit_skipped", unit=unit, file=self._paths[fi],
                       row_group=gi, rows=rows)

    def _adjusted_plan(self, plan):
        """Zero the quarantined units' rows in an epoch plan, so the cursor
        math (locate/starts) matches the stream that will actually flow —
        the restore half of the deterministic-skip contract."""
        if not self._skipped_units:
            return plan
        gids = np.asarray([int(self._my_units[o]) for o in plan.order],
                          dtype=np.int64)
        rows = plan.unit_rows.copy()
        rows[np.isin(gids, np.fromiter(self._skipped_units, dtype=np.int64))] = 0
        starts = np.zeros(len(rows) + 1, dtype=np.int64)
        np.cumsum(rows, out=starts[1:])
        return plan.__class__(epoch=plan.epoch, order=plan.order,
                              unit_rows=rows, starts=starts)

    def _decode_unit(self, unit: int) -> dict[str, np.ndarray]:
        """One (file, row group) unit -> {column: np.ndarray} host arrays.

        ``prefetch=K`` routes the unit through the PR-1 chunk pipeline
        (FileReader's io+CRC+decompress+decode of the unit's chunks, K-deep
        on a bounded pool) — on a 2-core host that is where the overlap
        actually pays (measured 1.3x vs 0.95x for unit-level threading,
        which just oversubscribes the cores against the consumer's shuffle
        work).  Output is bit-identical at every depth (the PR-1 contract).
        Each call opens its own fd; the cached footer skips the reparse.
        """
        from ..errors import DataIntegrityError
        from ..quarantine import annotate_data_error
        from ..reader import FileReader  # deferred: reader pulls numpy chains

        fi, gi = self._unit_map[unit]
        if fi in self._bad_files:
            # fast path for a skip_file-marked file: the consumer would
            # drop this unit's rows regardless (consumer-order decision),
            # so don't pay its decode.  Safe under lookahead: the flag is
            # only ever SET by the consumer, so a worker seeing it implies
            # the consumer will see it too.
            return _UnitSkipped(unit, None)
        try:
            # the inner reader must RAISE (never skip internally): the
            # loader's own seam owns unit granularity, the checkpointed
            # skip set, and the one shared budget/ledger
            with FileReader(self._paths[fi], columns=self._columns,
                            metadata=self._metas[fi],
                            validate_crc=self._validate_crc,
                            prefetch=self._prefetch,
                            quarantine=self._inner_quarantine) as r:
                if self._prefetch > 0:
                    cols = r.read_row_group(gi)
                    self._pstats.merge_from(r.pipeline_stats())
                else:
                    # the sequential path has no per-stage instrumentation, so
                    # the WHOLE read (IO included) books under "decompress" —
                    # loader-level timing lives in LoaderStats.decode_wait_seconds
                    # either way; the io/decompress split is only meaningful at
                    # prefetch > 0 (PipelineStats contract)
                    with self._pstats.timed("decompress"):
                        cols = r.read_row_group(gi, prefetch=0)
                    # the pipelined branch counts groups/chunks via the merge
                    self._pstats.count_row_group()
            n = self._unit_rows_all[unit]
            out = {}
            for name in self._colnames:
                cd = cols[name]
                if isinstance(cd.values, ByteArrayData) or cd.max_rep > 0:
                    # construction validates the schema; reaching here means
                    # the file's data contradicts its own footer
                    raise ParquetError(
                        f"column {name!r} is not fixed-width flat")
                if (cd.def_levels is not None
                        and cd.num_defined != cd.num_leaf_slots):
                    raise TypeError(
                        f"DataLoader needs null-free columns; {name!r} has "
                        f"{cd.num_leaf_slots - cd.num_defined} nulls"
                    )
                arr = np.asarray(cd.values)
                if len(arr) != n:
                    raise ParquetError(
                        f"column {name!r} decoded {len(arr)} rows, footer "
                        f"declares {n}"
                    )
                out[name] = arr
            return out
        except (ParquetError, TypeError) as e:
            # containment seam (quarantine.py): the unit becomes an in-band
            # skip marker instead of an epoch-killing raise; the CONSUMER
            # (_blocks) notes the record in stream order.  TypeError is
            # included because a corruption the CRC tier cannot see (no
            # checksum written) can surface as the null-free/fixed-width
            # contract check above.  Budget exhaustion (DataIntegrityError)
            # always propagates.
            if not self._quarantine.contains or isinstance(
                    e, DataIntegrityError):
                raise
            return _UnitSkipped(unit, annotate_data_error(
                e, file=self._paths[fi], row_group=gi, unit=unit,
                epoch=self._epoch))

    def _blocks(self, plan, first_block: int, skip_rows: int):
        """Yield (block_index, {col: raw rows}, permutation|None) shuffle
        blocks from ``first_block`` on; ``skip_rows`` rows of the first unit
        belong to earlier blocks and are dropped before buffering.

        Blocks are yielded UNPERMUTED with their seeded permutation: the
        batcher gathers each batch's rows straight through the permutation
        slice (one copy per row) instead of materializing a permuted block
        and copying batch slices out of it (two).

        Containment (quarantine.py): a unit arriving as a
        :class:`_UnitSkipped` marker is recorded and dropped — the block
        stream simply never sees its rows, so blocking/permutation over the
        SURVIVING rows is identical whether the skip was discovered live or
        replayed proactively from a restored checkpoint.  ``skip_file``
        marks the file bad; later units of a bad file are dropped on
        arrival even when their own decode succeeded in the lookahead (the
        decision is made in CONSUMER order, so it is deterministic at every
        prefetch depth)."""
        window = self._shuffle_window
        q = self._quarantine
        unit_ids = [int(self._my_units[plan.order[k]])
                    for k in range(len(plan.order))]
        # proactive skips: units already quarantined this epoch (a restored
        # skip set, or a bad file's not-yet-reached units) are never decoded
        # — their rows are zeroed in the caller's plan, so the cursor math
        # and this stream agree
        decode_ids = []
        for u in unit_ids:
            if u in self._skipped_units:
                continue
            if self._unit_map[u][0] in self._bad_files:
                self._note_unit_skip(u)
                continue
            decode_ids.append(u)
        # locate() already skipped fully-consumed units via first_block's
        # start row; the caller passes the permuted ordinal to start at
        budget = (InFlightBudget(self._max_memory)
                  if self._max_memory > 0 else None)
        cost = ((lambda u: self._unit_cost_all[u])
                if budget is not None else None)
        if budget is not None:
            self._budget = budget  # sampler's budget_waiters track
            wd = self._watchdog
            if wd is not None and wd.enabled:
                wd.add_abort_hook(budget.abort)
        # ONE unit of lookahead: the next unit's chunk pipeline runs while
        # the consumer permutes/batches the current one.  Deeper unit-level
        # fan-out only oversubscribes the cores the chunk pipeline already
        # uses (0.95x measured at depth 4 on 2 cores); the real depth knob
        # is the chunk pipeline inside _decode_unit.
        stream = prefetch_map(iter(decode_ids), self._decode_unit,
                              min(self._prefetch, 1), budget=budget,
                              cost=cost, stats=self._pstats)
        names = self._colnames
        parts: dict[str, list] = {c: [] for c in names}
        buffered = 0
        bidx = first_block
        pos = 0  # index into decode_ids, so each result names its unit
        tr = self._tracer
        try:
            while True:
                t0 = time.perf_counter()
                try:
                    arrays = next(stream)
                except StopIteration:
                    break
                t1 = time.perf_counter()
                uid = decode_ids[pos]
                pos += 1
                self._stats.decode_wait_seconds += t1 - t0
                if tr.active:
                    # consumer time blocked on the decode stream — the span
                    # that shrinks toward zero as prefetch hides the decode
                    tr.complete("decode_wait", t0, t1)
                if self._unit_map[uid][0] in self._bad_files:
                    # collateral skip of an already-bad file's unit —
                    # whether its decode succeeded in the lookahead, failed,
                    # or was fast-pathed away: dropped with NO new record
                    # and no budget charge (consumer-order decision =
                    # deterministic at every prefetch depth)
                    self._note_unit_skip(uid)
                    continue
                if isinstance(arrays, _UnitSkipped):
                    # quarantined: record (budget may raise), drop the unit
                    q.note(arrays.exc)
                    self._stats.data_errors += 1
                    self._note_unit_skip(uid)
                    if q.policy == "skip_file":
                        q.note_file_skipped()
                        self._bad_files.add(self._unit_map[uid][0])
                    continue
                if skip_rows:
                    arrays = {c: a[skip_rows:] for c, a in arrays.items()}
                    skip_rows = 0
                n = len(arrays[names[0]])
                if n == 0:
                    continue
                for c in names:
                    parts[c].append(arrays[c])
                buffered += n
                self._stats.window_peak_rows = max(
                    self._stats.window_peak_rows, buffered)
                if tr.enabled:  # counter track only: the ring wants spans
                    tr.counter("shuffle_window_rows", rows=buffered)
                while buffered >= window:
                    cat = {c: (np.concatenate(parts[c])
                               if len(parts[c]) > 1 else parts[c][0])
                           for c in names}
                    yield bidx, {c: a[:window] for c, a in cat.items()}, (
                        block_permutation(self._seed, plan.epoch,
                                          self._shard[0], bidx, window)
                        if self._shuffle else None)
                    bidx += 1
                    buffered -= window
                    parts = {c: ([cat[c][window:]] if buffered else [])
                             for c in names}
            if buffered:
                tail = {c: (np.concatenate(parts[c])
                            if len(parts[c]) > 1 else parts[c][0])
                        for c in names}
                yield bidx, tail, (
                    block_permutation(self._seed, plan.epoch, self._shard[0],
                                      bidx, buffered)
                    if self._shuffle else None)
        finally:
            self._budget = None
            wd = self._watchdog
            if budget is not None and wd is not None and wd.enabled:
                wd.remove_abort_hook(budget.abort)
            stream.close()

    def _emit(self, cols: dict, n: int):
        """Assemble one yielded batch: pad+mask the ragged tail, optionally
        ship to device."""
        mask_key = (self._mask_key
                    if not self._drop_remainder else None)
        batch = pad_and_mask(cols, n, self._batch_size, mask_key=mask_key)
        if self._to_device:
            batch = ship_to_device(batch)
        return batch

    def _batches(self, epoch: int, start_row: int):
        """Yield (batch, rows_consumed) for one epoch from ``start_row``."""
        plan = self._adjusted_plan(
            plan_epoch(self._seed, epoch, self._shard[0],
                       self._shard_unit_rows, self._shuffle))
        total = plan.total_rows
        if start_row >= total:
            return
        window = self._shuffle_window
        bs = self._batch_size
        first_block = start_row // window
        drop = start_row - first_block * window  # rows already consumed
        k0, skip = plan.locate(first_block * window)
        names = self._colnames
        # re-aim the unit stream at the first block's first unit
        sub = plan.__class__(epoch=plan.epoch, order=plan.order[k0:],
                             unit_rows=plan.unit_rows[k0:],
                             starts=plan.starts[k0:] - plan.starts[k0])
        pend: dict[str, list] = {c: [] for c in names}
        pend_n = 0
        blocks = self._blocks(sub, first_block, skip)
        try:
            for _bidx, block, perm in blocks:
                n = len(block[names[0]])
                pos = drop  # resume mid-block: emitted order == permuted order
                drop = 0
                while pos < n:
                    take = min(bs - pend_n, n - pos)
                    if perm is not None:
                        # fused shuffle+cut: each row gathers once, straight
                        # into its batch (take beats fancy indexing ~10%
                        # here and tolerates the idx slice being non-owned)
                        idx = perm[pos : pos + take]
                        piece = {c: np.take(block[c], idx, axis=0)
                                 for c in names}
                    else:
                        piece = {c: block[c][pos : pos + take].copy()
                                 for c in names}
                    pos += take
                    pend_n += take
                    for c in names:
                        pend[c].append(piece[c])
                    if pend_n == bs:
                        yield self._emit(
                            {c: (np.concatenate(pend[c])
                                 if len(pend[c]) > 1 else pend[c][0])
                             for c in names}, bs), bs
                        pend = {c: [] for c in names}
                        pend_n = 0
            if pend_n and not self._drop_remainder:
                tail = {c: (np.concatenate(pend[c])
                            if len(pend[c]) > 1 else pend[c][0])
                        for c in names}
                yield self._emit(tail, pend_n), pend_n
        finally:
            blocks.close()

    def __iter__(self):
        """Iterate the CURRENT epoch from the current cursor, then advance
        the epoch.  ``state()`` between batches is a valid resume point."""
        from ..obs import Sampler, Watchdog

        epoch = self._epoch
        stats = self._stats
        tr = self._tracer
        sampler = Sampler(tr, self._sample_ms,
                          track_id=self._pstats._obs_id)
        if sampler.enabled:
            sampler.add_source("loader_progress", lambda: {
                "rows": stats.rows, "batches": stats.batches,
                "decode_wait_seconds": round(stats.decode_wait_seconds, 6),
            })
            sampler.add_source("pipeline_lanes", self._pstats.sample)
            sampler.add_source("budget_waiters", lambda: (
                self._budget.snapshot() if self._budget is not None else {}))
            # quarantined-unit accounting as a live curve: a corruption
            # burst is visible next to the lanes it degraded
            sampler.add_source("data_errors", self._quarantine.progress)
            sampler.start()
        watchdog = Watchdog(self._hang_s, policy=self._hang_policy)
        lane = None
        if watchdog.enabled:
            watchdog.watch("loader", lambda: {
                "batches": stats.batches, "rows": stats.rows,
            })
            watchdog.watch("pipeline", self._pstats.sample)
            # consumer gate: a training loop pausing between batches (eval,
            # checkpoint) freezes every lane above — only a consumer
            # genuinely blocked in next() may read as a hang
            lane = watchdog.watch_consumer()
            self._watchdog = watchdog  # _blocks registers its budget's abort
            watchdog.start()
        # fleet spool (TPQ_OBS_SPOOL; inert when unset): this loader
        # process's registry + any cross-process request trace it adopted
        # (TPQ_TRACE_CONTEXT) become visible to the FleetAggregator
        from ..obs_fleet import SpoolWriter, ambient_request_trace

        spool_tr = ambient_request_trace()
        spool = SpoolWriter(
            self.obs_registry, role="loader",
            sampler=lambda: [spool_tr.as_dict()] if spool_tr else [])
        spool.start()
        # per-epoch error-budget scope: the fraction denominator is this
        # shard's unit count; the ledger and skip counters are cumulative
        self._quarantine.begin_scan(len(self._my_units))
        gen = self._batches(epoch, self._rows_taken)
        try:
            while True:
                if watchdog.enabled:
                    watchdog.check()  # surface a fired HangError even when
                    # no budget wait existed for the abort hook to interrupt
                # time each batch's PRODUCTION (decode + shuffle + assembly,
                # consumer wait excluded) as a "batch" span
                t0 = time.perf_counter()
                if lane is not None:
                    lane.producing()
                try:
                    batch, consumed = next(gen)
                except StopIteration:
                    break
                finally:
                    if lane is not None:
                        lane.idle()
                if tr.active:
                    tr.complete("batch", t0, time.perf_counter(),
                                rows=consumed)
                self._rows_taken += consumed
                stats.touch_wall()
                self._pstats.touch_wall()
                stats.batches += 1
                stats.rows += consumed
                if consumed < self._batch_size:
                    stats.padded_batches += 1
                yield batch
                stats.touch_wall()
        finally:
            watchdog.stop()  # thread-leak-safe even on early abandon
            self._watchdog = None
            sampler.stop()
            spool.stop()  # publishes a final generation, joins (no leak)
            gen.close()
            if self._owns_tracer:
                # per-loader trace artifact: rewrite (cumulatively) at every
                # epoch end or early abandon so the file exists without
                # waiting for interpreter exit
                self._tracer.write(registry=self.obs_registry())
        # epoch complete (also when resumed exactly at its end)
        self._epoch = epoch + 1
        self._rows_taken = 0
        # the skip set is an EPOCH fact: the next epoch re-attempts every
        # unit (a transient corruption heals; a persistent one re-records
        # under the fresh per-epoch budget)
        self._skipped_units = set()
        self._bad_files = set()
        stats.epochs_completed += 1

    def epochs(self, n: int):
        """Chain ``n`` epochs (continuing from the current cursor)."""
        for _ in range(int(n)):
            yield from self
