"""Overlapped chunk pipeline: threaded IO + decompress prefetch.

SURVEY.md §7.4.7's stance — "pipelining beats any single kernel" — applied to
the host side of the decode path.  The engine's natural work unit is the
column chunk (one contiguous IO, one decompress+parse, one staged region, one
fused dispatch); until this module, file → row group → chunk executed strictly
sequentially, so the device idled during every chunk's IO and the CPU idled
during every transfer.

Three pieces, shared by the host ``FileReader`` and the batched
``DeviceFileReader``:

- :func:`prefetch_map` — an *ordered* overlapped map: up to ``prefetch``
  items run on a bounded thread pool ahead of the consumer, results are
  yielded in submission order, and errors surface at the failing item's
  position (never out of order, never swallowed).  Decompression releases the
  GIL (zlib via stdlib, snappy via ctypes → the C++ codec), and chunk IO is
  blocking reads, so host threads genuinely overlap.  The item stream is
  pulled lazily in the CONSUMER thread, so work generation (page-pruning
  planning, schema snapshots) keeps its sequential semantics.
- :class:`PipelineStats` — per-stage wall-time counters
  (io / decompress / stage / dispatch / finalize) plus stall time and the
  in-flight high-water mark, surfaced by both readers' ``pipeline_stats()``
  so bench.py can report overlap efficiency (sum of stage time ÷ wall time:
  1.0 is perfectly serial, higher means overlap).
- :class:`SharedReader` — thread-safe positioned reads over one byte source:
  ``os.pread`` on real files (parallel, never touches the shared fd
  position), a lock around seek+read otherwise (BytesIO, sockets wrapped in
  a buffer).

Memory is bounded by :class:`tpu_parquet.alloc.InFlightBudget`: the submitter
acquires each chunk's estimated bytes (compressed + decompressed, from the
footer) BEFORE handing it to the pool and releases them when the consumer
takes the result — backpressure instead of OOM, asserted in tests.  The
budget is only ever awaited in the consumer thread while nothing is in
flight, or skipped in favor of draining the window head, so it cannot
deadlock against itself.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from contextlib import contextmanager
from typing import Callable, Iterable, Iterator, Optional, TypeVar

from .alloc import InFlightBudget
from .obs import (LatencyHistogram, current_tracer, note_worker_crash,
                  register_flight_source)

T = TypeVar("T")
R = TypeVar("R")

STAGES = ("io", "decompress", "recompress", "stage", "dispatch", "finalize")

# per-PipelineStats token riding the pipeline_wall trace counter: one trace
# often carries several stats objects (one per file of a scan), and the
# summarizer must sum each pipeline's own wall, not max across all of them
_pstats_ids = itertools.count(1)


class PipelineStats:
    """Per-stage timing for the overlapped decode pipeline (SURVEY.md §5.5).

    Stage meanings (a stage a path never enters simply stays 0):

    - ``io``          chunk byte reads from the source
    - ``decompress``  page decompress + CRC + structure parse + host decode
    - ``recompress``  link recompression: snappy over hot streams so GZIP/
                      ZSTD/uncompressed files still ship compressed
                      (ship.py ROUTE_RECOMPRESS; runs on the same worker
                      threads as decompress when prefetch > 0)
    - ``stage``       host→device staging (buffer assembly + transfer)
    - ``dispatch``    issuing the fused XLA calls
    - ``finalize``    deferred validity syncs

    ``busy_seconds`` is the sum over stages — the serial cost the pipeline is
    hiding; ``overlap_efficiency = busy_seconds / wall_seconds`` reads 1.0
    for a perfectly serial run and >1 when stages genuinely overlap.
    ``stall_seconds`` counts submitter time blocked on the memory budget.
    Thread-safe: workers and the main thread add concurrently.

    Each ``add``/``timed`` also feeds a per-stage log-bucketed
    :class:`~tpu_parquet.obs.LatencyHistogram` (p50/p95 where the sums
    alone can't attribute a stall — see obs.StatsRegistry), and ``timed``
    emits a span on ``tracer`` (the ``TPQ_TRACE`` process tracer by
    default; a disabled tracer costs one ``if``).
    """

    def __init__(self, prefetch: int = 0, budget_bytes: int = 0,
                 tracer=None):
        self.prefetch = int(prefetch)
        self.budget_bytes = int(budget_bytes)
        self.chunks = 0
        self.row_groups = 0
        self.stall_seconds = 0.0
        self.wall_seconds = 0.0
        self.peak_in_flight_bytes = 0
        # live prefetch backlog (items submitted, not yet consumed) — a
        # point-in-time gauge for obs.Sampler, deliberately NOT in as_dict()
        # (its end-of-run value is always 0 and would only add key noise)
        self.queue_depth = 0
        self._stage_seconds = {s: 0.0 for s in STAGES}
        self._stage_hist = {s: LatencyHistogram() for s in STAGES}
        self.tracer = tracer if tracer is not None else current_tracer()
        self._obs_id = next(_pstats_ids)
        self._lock = threading.Lock()
        self._t0: Optional[float] = None
        # a flight dump must show every live pipeline's lane seconds and
        # queue depth at the moment of the wedge (weakly held — see obs)
        register_flight_source(f"pipeline[{self._obs_id}]", self, "sample")

    # -- accumulation ---------------------------------------------------------

    def add(self, stage: str, seconds: float) -> None:
        if stage not in self._stage_seconds:
            raise ValueError(
                f"unknown pipeline stage {stage!r}; valid stages: "
                f"{', '.join(STAGES)}")
        with self._lock:
            self._stage_seconds[stage] += seconds
        self._stage_hist[stage].record(seconds)

    @contextmanager
    def timed(self, stage: str, **span_args):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            self.add(stage, t1 - t0)
            tr = self.tracer
            if tr is not None and tr.active:
                tr.complete(stage, t0, t1, **span_args)

    def add_stall(self, seconds: float, t0: Optional[float] = None) -> None:
        with self._lock:
            self.stall_seconds += seconds
        tr = self.tracer
        if tr is not None and tr.active and t0 is not None:
            tr.complete("stall", t0, t0 + seconds)

    def count_chunk(self) -> None:
        with self._lock:
            self.chunks += 1

    def count_row_group(self) -> None:
        with self._lock:
            self.row_groups += 1

    def touch_wall(self) -> None:
        """Extend the wall clock to now (first call starts it)."""
        now = time.perf_counter()
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            self.wall_seconds = now - self._t0
            wall = self.wall_seconds
        tr = self.tracer
        if tr is not None and tr.active and wall:
            # the pipeline's own wall clock rides the trace as a counter so
            # pq_tool trace reports the SAME overlap efficiency as this
            # object (span extents alone include consumer tails the wall
            # clock deliberately excludes)
            tr.counter("pipeline_wall", seconds=round(wall, 6),
                       pipe=self._obs_id)

    def set_queue_depth(self, n: int) -> None:
        with self._lock:
            self.queue_depth = int(n)

    def sample(self) -> dict:
        """Point-in-time counter snapshot for :class:`~tpu_parquet.obs.Sampler`:
        the cumulative per-stage seconds (as counter tracks their slope IS
        live per-lane throughput), the stall total, and the live prefetch
        queue depth (backpressure visible as a curve, not an end total)."""
        with self._lock:
            out = {s: round(v, 6) for s, v in self._stage_seconds.items()}
            out["stall"] = round(self.stall_seconds, 6)
            out["chunks"] = self.chunks
            out["queue_depth"] = self.queue_depth
        return out

    def note_peak(self, budget: InFlightBudget) -> None:
        with self._lock:
            self.peak_in_flight_bytes = max(self.peak_in_flight_bytes,
                                            budget.peak)

    def merge_from(self, other: "PipelineStats") -> None:
        """Fold another pipeline's counters into this one (layering hook:
        a DataLoader accumulates its per-unit readers' pipelines here).
        Stage/stall seconds and item counts add; peaks take the max; the
        wall clock stays this object's own (merged pipelines overlap it)."""
        with other._lock:
            stages = dict(other._stage_seconds)
            chunks, row_groups = other.chunks, other.row_groups
            stall = other.stall_seconds
            peak = other.peak_in_flight_bytes
        with self._lock:
            for s, v in stages.items():
                self._stage_seconds[s] += v
            self.chunks += chunks
            self.row_groups += row_groups
            self.stall_seconds += stall
            self.peak_in_flight_bytes = max(self.peak_in_flight_bytes, peak)
        for s in STAGES:
            self._stage_hist[s].merge_from(other._stage_hist[s])

    # -- reporting ------------------------------------------------------------

    def stage_seconds(self, stage: str) -> float:
        with self._lock:
            return self._stage_seconds[stage]

    @property
    def busy_seconds(self) -> float:
        with self._lock:
            return sum(self._stage_seconds.values())

    @property
    def overlap_efficiency(self) -> float:
        return self.busy_seconds / self.wall_seconds if self.wall_seconds else 0.0

    def as_dict(self) -> dict:
        with self._lock:
            stages = {f"{s}_seconds": round(v, 6)
                      for s, v in self._stage_seconds.items()}
        busy = self.busy_seconds
        return {
            "prefetch": self.prefetch,
            "budget_bytes": self.budget_bytes,
            "chunks": self.chunks,
            "row_groups": self.row_groups,
            **stages,
            "busy_seconds": round(busy, 6),
            "wall_seconds": round(self.wall_seconds, 6),
            "stall_seconds": round(self.stall_seconds, 6),
            "peak_in_flight_bytes": self.peak_in_flight_bytes,
            "overlap_efficiency": round(self.overlap_efficiency, 3),
            # only the stages that saw work: the empty ones carry no
            # information and would triple the artifact's size
            "stage_histograms": {s: h.as_dict()
                                 for s, h in self._stage_hist.items()
                                 if h.count},
        }


class SharedReader:
    """Thread-safe positioned reads over one byte source, via a ByteStore.

    Every read delegates to a :class:`tpu_parquet.iostore.ByteStore` —
    :class:`~tpu_parquet.iostore.LocalStore` by default (``os.pread`` on
    real files: fully parallel, the shared fd's position is never touched,
    so a main thread interleaving its own seek+read — the page-pruning
    planner — stays correct; a lock around seek+read for fd-less sources).
    Passing a :class:`~tpu_parquet.iostore.GenericRangeStore` slots the
    fault-tolerant retry/backoff/deadline core underneath the SAME reader
    and pipeline stack — no decode layer sees the difference.
    ``parallel`` is False on the locked path so callers that ALSO seek the
    raw object outside this class know to stay sequential.
    """

    def __init__(self, f, store=None):
        self._f = f
        if store is None:
            from .iostore import LocalStore

            store = LocalStore(f)
        self.store = store
        # the CURRENT scan's iostore.ScanToken (set by the reader at each
        # scan boundary): rides every pread so a store shared between
        # concurrent requests charges THIS scan's retry budget and honors
        # THIS request's deadline/cancel — never a neighbor's
        self._scan = None

    def set_scan(self, token) -> None:
        self._scan = token

    @property
    def parallel(self) -> bool:
        return self.store.parallel

    def as_file(self) -> "_PReadFile":
        """A minimal file-like (seek/read pairs) whose every read goes
        through ``pread`` — for code written against a raw file that must
        run while worker threads read the same source (the page-pruning
        planner's header walks)."""
        return _PReadFile(self)

    def pread(self, offset: int, size: int) -> bytes:
        if self._scan is not None:
            return self.store.read_range(offset, size, scan=self._scan)
        return self.store.read_range(offset, size)


class _PReadFile:
    """File-like adapter over :class:`SharedReader` — tracks its own
    position, so concurrent holders never fight over the shared fd's."""

    def __init__(self, sr: SharedReader):
        self._sr = sr
        self._pos = 0

    def seek(self, pos: int) -> int:
        self._pos = int(pos)
        return self._pos

    def read(self, size: int) -> bytes:
        b = self._sr.pread(self._pos, size)
        self._pos += len(b)
        return b


def prefetch_map(
    items: Iterable[T],
    fn: Callable[[T], R],
    prefetch: int,
    budget: Optional[InFlightBudget] = None,
    cost: Optional[Callable[[T], int]] = None,
    stats: Optional[PipelineStats] = None,
    cancel=None,
    feed=None,
) -> Iterator[R]:
    """Ordered overlapped map: run ``fn`` over ``items`` on a bounded pool.

    Up to ``prefetch`` items are in flight ahead of the consumer; results
    yield strictly in item order; an item whose ``fn`` raises re-raises at
    its ordered position, after which remaining work is cancelled and the
    pool is joined — no leaked threads, even when the consumer abandons the
    generator early (``break`` triggers the same cleanup via close()).

    ``cost(item)`` bytes are acquired from ``budget`` before submission and
    released when the consumer receives the result (ownership transfers).
    Backpressure never blocks while results are poppable: when the next
    item's bytes don't fit, the window head is drained first; a true blocking
    wait happens only with nothing in flight (the oversize-item case, which
    :class:`InFlightBudget` admits alone).

    ``cancel`` (a :class:`~tpu_parquet.resilience.CancelToken`) is checked
    at every unit boundary — before each submission and each yield — so a
    cancelled or deadline-expired request stops issuing new work, raises
    its TYPED verdict at the consumer, and still runs the full cleanup
    path (window drained, budget released, pool joined: nothing orphaned).

    ``feed`` (a :class:`tpu_parquet.iostore_async.FetchEngine`, or any
    object with ``want_more()``/``max_inflight``) decouples IO depth from
    decode depth: pulling an item is what SUBMITS its IO (the engine-mode
    :class:`~tpu_parquet.iostore.CoalescedFetcher` puts its ranges in
    flight at construction), so while the engine reports free fetch slots,
    items are pulled ahead of the ``prefetch``-deep decode window into a
    ready queue — ``prefetch=K`` bounds DECODE parallelism, in-flight IO
    is bounded by ``TPQ_IO_INFLIGHT`` and the memory budget (ahead-pulls
    charge ``budget`` non-blocking and stop at the first refusal, so
    backpressure still holds).

    ``prefetch <= 0`` degrades to a plain sequential map with zero threads —
    the bit-identical baseline the tests compare against.
    """
    trace = getattr(cancel, "trace", None) if cancel is not None else None
    if prefetch <= 0:
        for item in items:
            if cancel is not None:
                cancel.check()
            if trace is None:
                yield fn(item)
            else:
                with trace.span("decode"):
                    res = fn(item)
                yield res
        return

    def run(item):
        # the worker half of the flight recorder's crash trigger: a dying
        # worker notes itself in the ring (and dumps under TPQ_FLIGHT)
        # BEFORE the future carries the exception back — the consumer may
        # be blocked elsewhere and never surface it promptly
        try:
            if trace is None:
                return fn(item)
            # one request-trace span per decoded unit, on the worker
            # thread (per-thread nesting parents it to the request root)
            with trace.span("decode"):
                return fn(item)
        except BaseException as e:
            note_worker_crash(e)
            raise

    it = iter(items)
    pending: deque = deque()  # (future, charged_cost)
    ready: deque = deque()    # (item, cost): charged + IO submitted, awaiting
    #                           a decode slot (only the feed pulls ahead here)
    carried: Optional[tuple] = None  # (item, cost) awaiting budget headroom
    # the WINDOW is prefetch items deep, but the pool never exceeds the
    # machine's cores: chunk decode is a numpy/ctypes mix that still holds
    # the GIL between releases, and oversubscribed workers convoy on it
    # (measured 0.88x at 4 threads on 2 cores; queued-but-not-running items
    # keep the lookahead without the contention)
    workers = max(1, min(prefetch, os.cpu_count() or 1))
    ex = ThreadPoolExecutor(max_workers=workers,
                            thread_name_prefix="tpq-prefetch")
    try:
        exhausted = False

        def pull(block_ok: bool) -> bool:
            # move ONE item generator → (budget charge) → ready queue;
            # False when the generator is dry or the budget said not now.
            # A blocking budget wait is allowed only with nothing in
            # flight (block_ok + empty window) — the no-deadlock contract
            nonlocal carried, exhausted
            if carried is None:
                try:
                    item = next(it)
                except StopIteration:
                    exhausted = True
                    return False
                carried = (item, int(cost(item)) if cost is not None else 0)
            item, c = carried
            if budget is not None and c:
                if not budget.try_acquire(c):
                    if not block_ok or pending:
                        return False  # drain the head; its release frees room
                    t0 = time.perf_counter()
                    budget.acquire(c, cancel=cancel)
                    if stats is not None:
                        stats.add_stall(time.perf_counter() - t0, t0)
                if stats is not None:
                    stats.note_peak(budget)
            carried = None
            ready.append((item, c))
            return True

        while True:
            if cancel is not None:
                # the unit-boundary gate: stop issuing new IO the moment
                # the request is cancelled/expired; the finally below still
                # drains the window and releases every charged byte
                cancel.check()
            while len(pending) < prefetch:
                if not ready and (exhausted or not pull(block_ok=True)):
                    break
                item, c = ready.popleft()
                pending.append((ex.submit(run, item), c))
                if stats is not None:
                    stats.set_queue_depth(len(pending))
            if feed is not None:
                # the engine-backed lookahead: pulling submits IO, so keep
                # pulling while the engine has free fetch slots (and the
                # ready backlog stays bounded); never block on the budget
                while (not exhausted and len(ready) < feed.max_inflight
                       and feed.want_more() and pull(block_ok=False)):
                    pass
            if not pending:
                if exhausted and carried is None and not ready:
                    break
                continue  # budget-carried item with empty window: block-acquire
            fut, c = pending.popleft()
            if stats is not None:
                stats.set_queue_depth(len(pending))
            try:
                res = fut.result()
            finally:
                if budget is not None and c:
                    budget.release(c)
            yield res
    finally:
        if stats is not None:
            stats.set_queue_depth(0)
        for fut, _c in pending:
            fut.cancel()
        ex.shutdown(wait=True)
        for fut, c in pending:
            if budget is not None and c:
                budget.release(c)
            if not fut.cancelled():
                fut.exception()  # retrieve, so failures aren't warned as lost
        for _item, c in ready:
            # ahead-pulled items never reached the pool: refund their charge
            if budget is not None and c:
                budget.release(c)
