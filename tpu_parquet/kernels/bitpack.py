"""Bit-packing primitives (LSB-first, parquet RLE/bit-packed hybrid layout).

The reference generates 98 width-specialized unrolled Go functions
(bitpack_gen.go:48-165 → bitbacking32.go / bitpacking64.go, 4.5k LoC).  Here a single
vectorized transform handles every width 0–64: unpack the byte stream to a bit matrix
(LSB-first within each byte, matching the parquet spec) and reduce against powers of
two.  The same math runs under NumPy (host) and jnp (device, jax_kernels.py).
"""

from __future__ import annotations

import numpy as np

__all__ = ["unpack", "pack", "bit_width"]


def bit_width(v: int) -> int:
    """Number of bits required to represent v (0 → 0). Mirrors bits.Len semantics."""
    return int(v).bit_length()


def unpack(data: bytes | np.ndarray, width: int, count: int) -> np.ndarray:
    """Unpack ``count`` unsigned values of ``width`` bits from an LSB-first stream.

    Returns uint32 for width<=32, uint64 otherwise.  Input may be longer than
    needed; excess bits/bytes are ignored.
    """
    out_dtype = np.uint32 if width <= 32 else np.uint64
    if width == 0:
        return np.zeros(count, dtype=out_dtype)
    if count == 0:
        return np.zeros(0, dtype=out_dtype)
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    need_bytes = (count * width + 7) // 8
    if len(buf) < need_bytes:
        raise ValueError(
            f"bitpack underflow: need {need_bytes} bytes for {count}x{width}b, have {len(buf)}"
        )
    bits = np.unpackbits(buf[:need_bytes], bitorder="little")
    total = count * width
    bits = bits[:total].reshape(count, width)
    weights = (np.uint64(1) << np.arange(width, dtype=np.uint64))
    vals = bits.astype(np.uint64) @ weights
    return vals.astype(out_dtype, copy=False)


def pack(values: np.ndarray, width: int) -> bytes:
    """Pack unsigned values into an LSB-first bit stream, padded to whole bytes.

    Inverse of :func:`unpack`.  Values must already fit in ``width`` bits.
    Runs in C when the native library is available (~25x: the numpy form
    expands an (n, width) bit matrix — it was the dict-string writer's
    hottest cost); the numpy path is the reference and fallback.
    """
    if width == 0 or len(values) == 0:
        return b""
    from .. import native

    out = native.bp_pack(values, width)
    if out is not None:
        return out.tobytes()
    vals = np.asarray(values, dtype=np.uint64)
    shifts = np.arange(width, dtype=np.uint64)
    bits = ((vals[:, None] >> shifts) & np.uint64(1)).astype(np.uint8)
    return np.packbits(bits.reshape(-1), bitorder="little").tobytes()
