"""PLAIN encoding codecs for all 8 physical types.

Replaces the reference's per-type value-at-a-time plain decoders
(type_int32.go:11-53, type_int64.go, type_int96.go:15-66, type_float.go,
type_double.go, type_boolean.go:10-98, type_bytearray.go:13-96) with bulk
numpy bitcasts — PLAIN decode of fixed-width types is a zero-copy view.

INT96 is decoded as a (n, 3) uint32 little-endian matrix (12 bytes per value);
int96_time helpers convert to timestamps.  BYTE_ARRAY decodes to
(offsets, heap) — the length-prefix walk is the only sequential part and has a
vectorized two-pass implementation below.
"""

from __future__ import annotations

from ..errors import ParquetError

import numpy as np

from ..column import ByteArrayData
from ..format import Type

__all__ = ["decode", "encode", "decode_byte_array", "encode_byte_array"]


class PlainError(ParquetError):
    pass


_FIXED = {
    Type.INT32: np.dtype("<i4"),
    Type.INT64: np.dtype("<i8"),
    Type.FLOAT: np.dtype("<f4"),
    Type.DOUBLE: np.dtype("<f8"),
}


def decode(
    buf: bytes, ptype: int, count: int, type_length: int = 0
) -> "np.ndarray | ByteArrayData":
    """Decode ``count`` PLAIN values of physical type ``ptype`` from ``buf``."""
    ptype = Type(ptype)
    if ptype in _FIXED:
        dt = _FIXED[ptype]
        need = count * dt.itemsize
        if len(buf) < need:
            raise PlainError(
                f"plain {ptype.name}: need {need} bytes for {count} values, have {len(buf)}"
            )
        return np.frombuffer(buf, dt, count).copy()
    if ptype == Type.INT96:
        need = count * 12
        if len(buf) < need:
            raise PlainError(f"plain INT96: need {need} bytes, have {len(buf)}")
        return np.frombuffer(buf, "<u4", count * 3).reshape(count, 3).copy()
    if ptype == Type.BOOLEAN:
        need = (count + 7) // 8
        if len(buf) < need:
            raise PlainError(f"plain BOOLEAN: need {need} bytes, have {len(buf)}")
        bits = np.unpackbits(
            np.frombuffer(buf, np.uint8, need), bitorder="little"
        )
        return bits[:count].astype(bool)
    if ptype == Type.FIXED_LEN_BYTE_ARRAY:
        if type_length <= 0:
            raise PlainError(f"FIXED_LEN_BYTE_ARRAY needs positive type_length")
        need = count * type_length
        if len(buf) < need:
            raise PlainError(f"plain FIXED: need {need} bytes, have {len(buf)}")
        heap = np.frombuffer(buf, np.uint8, need).copy()
        offsets = np.arange(count + 1, dtype=np.int64) * type_length
        return ByteArrayData(offsets=offsets, heap=heap)
    if ptype == Type.BYTE_ARRAY:
        return decode_byte_array(buf, count)
    raise PlainError(f"unsupported physical type {ptype}")


def decode_byte_array(buf: bytes, count: int) -> ByteArrayData:
    """Decode length-prefixed BYTE_ARRAY values (uint32 LE length + bytes each).

    The prefix walk is inherently sequential (each length tells where the next
    one is), but only over ``count`` header positions — two passes over a small
    int array, no per-byte Python loop.  Runs in C when the native library is
    available (native/meta_parse.cpp tpq_bytearray_walk, identical semantics);
    the Python walk below is the reference and no-toolchain fallback.
    """
    if count > 0:
        from .. import native

        # no bytes() copy: the native wrapper takes any contiguous buffer
        res = native.bytearray_walk(buf, count)
        if isinstance(res, tuple):
            offsets, heap = res
            return ByteArrayData(offsets=offsets, heap=heap)
        if isinstance(res, int):
            if res == -20:
                raise PlainError("byte array: truncated length prefix")
            raise PlainError("byte array: length exceeds buffer")
    data = np.frombuffer(buf, dtype=np.uint8)
    n = len(data)
    starts = np.empty(count, dtype=np.int64)
    lens = np.empty(count, dtype=np.int64)
    pos = 0
    # Pass 1: walk headers. A Python loop over `count` items; replaced by the
    # native C++ walker when available (kept as clear fallback).
    buf_mv = memoryview(buf)
    for i in range(count):
        if pos + 4 > n:
            raise PlainError(f"byte array {i}: truncated length prefix")
        ln = int.from_bytes(buf_mv[pos : pos + 4], "little")
        if pos + 4 + ln > n:
            raise PlainError(f"byte array {i}: length {ln} exceeds buffer")
        starts[i] = pos + 4
        lens[i] = ln
        pos += 4 + ln
    offsets = np.zeros(count + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    total = int(offsets[-1])
    if total == 0:
        return ByteArrayData(offsets=offsets, heap=np.zeros(0, dtype=np.uint8))
    row_of = np.repeat(np.arange(count, dtype=np.int64), lens)
    within = np.arange(total, dtype=np.int64) - np.repeat(offsets[:-1], lens)
    heap = data[starts[row_of] + within]
    return ByteArrayData(offsets=offsets, heap=heap)


def encode(values, ptype: int, type_length: int = 0) -> bytes:
    """PLAIN-encode values (inverse of :func:`decode`)."""
    out = encode_view(values, ptype, type_length)
    return out if isinstance(out, bytes) else out.tobytes()


def encode_view(values, ptype: int, type_length: int = 0):
    """PLAIN-encode; fixed-width types return a zero-copy uint8 VIEW of the
    (contiguous) value array instead of bytes — the writer compresses the
    buffer directly, and the per-page tobytes copy was ~25% of a plain
    int64 chunk write."""
    ptype = Type(ptype)
    if ptype in _FIXED:
        arr = np.ascontiguousarray(values, dtype=_FIXED[ptype])
        return arr.view(np.uint8).reshape(-1)
    if ptype == Type.INT96:
        arr = np.ascontiguousarray(values, dtype="<u4")
        if arr.ndim != 2 or arr.shape[1] != 3:
            raise PlainError("INT96 values must be (n, 3) uint32")
        return arr.tobytes()
    if ptype == Type.BOOLEAN:
        bits = np.asarray(values, dtype=bool).astype(np.uint8)
        return np.packbits(bits, bitorder="little").tobytes()
    if ptype == Type.FIXED_LEN_BYTE_ARRAY:
        ba = values if isinstance(values, ByteArrayData) else ByteArrayData.from_list(values)
        lens = ba.offsets[1:] - ba.offsets[:-1]
        if type_length > 0 and not np.all(lens == type_length):
            raise PlainError(
                f"FIXED_LEN_BYTE_ARRAY({type_length}): got lengths {set(lens.tolist())}"
            )
        return ba.heap.tobytes()
    if ptype == Type.BYTE_ARRAY:
        ba = values if isinstance(values, ByteArrayData) else ByteArrayData.from_list(values)
        return encode_byte_array(ba)
    raise PlainError(f"unsupported physical type {ptype}")


def encode_byte_array(ba: ByteArrayData) -> bytes:
    """Interleave uint32 LE length prefixes with value bytes, vectorized."""
    n = len(ba)
    lens = (ba.offsets[1:] - ba.offsets[:-1]).astype(np.int64)
    total = int(ba.offsets[-1]) + 4 * n
    out = np.empty(total, dtype=np.uint8)
    # output start of each record = old offset + 4*i
    rec_starts = ba.offsets[:-1] + 4 * np.arange(n, dtype=np.int64)
    # write length prefixes
    len32 = lens.astype("<u4").view(np.uint8).reshape(n, 4)
    idx = rec_starts[:, None] + np.arange(4, dtype=np.int64)[None, :]
    out[idx.reshape(-1)] = len32.reshape(-1)
    # write payloads
    if int(ba.offsets[-1]) > 0:
        row_of = np.repeat(np.arange(n, dtype=np.int64), lens)
        within = np.arange(int(ba.offsets[-1]), dtype=np.int64) - np.repeat(
            ba.offsets[:-1], lens
        )
        out[rec_starts[row_of] + 4 + within] = ba.heap
    return out.tobytes()
