"""DELTA_BINARY_PACKED codec (parquet delta encoding for INT32/INT64).

Wire format (deltabp_decoder.go:13-333 semantics, parquet-format Encodings.md):

    header     := uvarint block_size, uvarint miniblocks_per_block,
                  uvarint total_value_count, zigzag-varint first_value
    block      := zigzag-varint min_delta,
                  byte[miniblocks_per_block] bit_widths,
                  miniblock* (each: values_per_miniblock deltas, bit-packed LSB-first)
    value[i]   := value[i-1] + min_delta + unpacked_delta[i]

The reference decodes one value at a time through two near-identical int32/int64
decoders; here header+bitwidth metadata is parsed on the host and the value
reconstruction is a single vectorized cumulative sum — the exact transform that
runs on-device in jax_kernels.py (prefix scan on the MXU-adjacent VPU).

Writer geometry matches the reference defaults: block_size=128,
miniblocks_per_block=4 (chunk_writer.go:53-57).
"""

from __future__ import annotations

from ..errors import ParquetError

import numpy as np

from . import bitpack

__all__ = ["decode", "encode", "parse_headers", "native_headers", "python_headers"]


class DeltaError(ParquetError):
    pass


def _read_uvarint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    n = len(buf)
    while True:
        if pos >= n:
            raise DeltaError("truncated varint in delta header")
        b = int(buf[pos])  # int(): numpy uint8 would wrap under << shift
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise DeltaError("varint too long in delta header")


def _read_zigzag(buf: bytes, pos: int) -> tuple[int, int]:
    v, pos = _read_uvarint(buf, pos)
    return (v >> 1) ^ -(v & 1), pos


def native_headers(buf: bytes, pos: int = 0):
    """Native (C) header walk; None when the library is unavailable.

    Returns (first, starts int64[M] bit positions, widths int32[M],
    mins uint64[M] per-miniblock min_delta, values_per_mini, total, consumed)
    or raises DeltaError on malformed streams.
    """
    from .. import native

    # one miniblock costs >= its width-vector byte, so len(buf) bounds the
    # miniblock count even for hostile headers; +4 covers tiny streams
    got = native.delta_meta(buf, pos, len(buf) - pos + 4)
    if got is None:
        return None
    if isinstance(got, int):
        if got == -10:  # cap retry exhausted: let the Python walk diagnose
            return None
        from ..native import NATIVE_ERRORS

        raise DeltaError(NATIVE_ERRORS.get(got, f"delta parse error {got}"))
    header, starts, widths, mins = got
    block_size, minis_per_block, total, first, consumed, _ = (
        int(x) for x in header
    )
    return (first, starts, widths, mins, block_size // minis_per_block,
            total, consumed)


def parse_headers(buf: bytes, pos: int = 0):
    """Walk the stream's block/miniblock headers (native C when available).

    Same return shape as :func:`native_headers`.  The single source of truth
    for delta-stream structure: this host decoder and the device path
    (jax_decode.parse_delta_meta) both build on it, and the fuzzer replays
    both walks for parity (fuzz.py).
    """
    got = native_headers(buf, pos)
    if got is not None:
        return got
    return python_headers(buf, pos)


def python_headers(buf: bytes, pos: int = 0):
    """Python reference walk (no-toolchain fallback; fuzz parity oracle)."""
    block_size, pos = _read_uvarint(buf, pos)
    minis_per_block, pos = _read_uvarint(buf, pos)
    total, pos = _read_uvarint(buf, pos)
    first, pos = _read_zigzag(buf, pos)
    if block_size == 0 or block_size % 128 != 0:
        raise DeltaError(f"invalid delta block size {block_size}")
    if block_size > 1 << 30:  # decompression-bomb guard (parity: meta_parse.cpp)
        raise DeltaError(f"implausible delta block size {block_size}")
    if minis_per_block == 0 or block_size % minis_per_block != 0:
        raise DeltaError(f"invalid miniblock count {minis_per_block}")
    values_per_mini = block_size // minis_per_block
    if values_per_mini % 32 != 0:
        raise DeltaError(f"miniblock size {values_per_mini} not multiple of 32")
    if total > 1 << 40:
        raise DeltaError(f"implausible delta value count {total}")
    starts, widths, mins = [], [], []
    got_d = 0
    n_deltas = max(total - 1, 0)
    while got_d < n_deltas:
        min_delta, pos = _read_zigzag(buf, pos)
        if pos + minis_per_block > len(buf):
            raise DeltaError("truncated miniblock bit widths")
        wvec = buf[pos : pos + minis_per_block]
        pos += minis_per_block
        for m in range(minis_per_block):
            if got_d >= n_deltas:
                break  # trailing miniblocks of a partial block may be absent
            w = wvec[m]
            if w > 64:
                raise DeltaError(f"invalid miniblock bit width {w}")
            nbytes = (values_per_mini * w + 7) // 8
            if pos + nbytes > len(buf):
                raise DeltaError("truncated miniblock data")
            starts.append(pos * 8)
            widths.append(w)
            mins.append(min_delta & 0xFFFFFFFFFFFFFFFF)
            pos += nbytes
            got_d += min(values_per_mini, n_deltas - got_d)
    return (
        first,
        np.asarray(starts, dtype=np.int64),
        np.asarray(widths, dtype=np.int32),
        np.asarray(mins, dtype=np.uint64),
        values_per_mini, total, pos,
    )


def decode(buf: bytes, bits: int = 64) -> tuple[np.ndarray, int]:
    """Decode a DELTA_BINARY_PACKED stream.

    Returns (values, bytes_consumed).  ``bits`` selects int32 vs int64 output
    (the two decoder copies in deltabp_decoder.go).  Arithmetic wraps modulo
    2^bits, matching the reference's Go integer overflow semantics on the
    min-delta edge cases its encoder exercises (deltabp_encoder.go:57-76).

    One vectorized pass over all deltas (the host twin of
    jax_kernels.delta_reconstruct): headers are walked in C, then every
    delta's bits are gathered with byte-indexed numpy arithmetic — no
    per-miniblock Python loop (which cost ~10x the whole decode).
    """
    first, starts, widths, mins, values_per_mini, total, pos = parse_headers(buf)

    out_dtype = np.int32 if bits == 32 else np.int64
    if total == 0:
        return np.zeros(0, dtype=out_dtype), pos
    if total == 1:
        return np.array([first], dtype=np.int64).astype(out_dtype), pos

    n_deltas = total - 1
    # padded copy of the packed bytes so the widest gather stays in bounds
    arr = np.empty(len(buf) + 9, dtype=np.uint8)
    arr[: len(buf)] = np.frombuffer(buf, dtype=np.uint8)
    arr[len(buf):] = 0

    i = np.arange(n_deltas, dtype=np.int64)
    m = i // values_per_mini
    within = i % values_per_mini
    w = widths[m].astype(np.int64)
    bit_pos = starts[m] + within * w
    byte0 = bit_pos >> 3
    shift = (bit_pos & 7).astype(np.uint64)
    max_w = int(widths.max(initial=0))
    acc = np.zeros(n_deltas, dtype=np.uint64)
    for k in range((min(max_w, 57) + 7 + 7) // 8):
        acc |= arr[byte0 + k].astype(np.uint64) << np.uint64(8 * k)
    out = acc >> shift
    if max_w > 57:  # field may span 9 bytes: OR the straggler above 64-shift
        b8 = arr[byte0 + 8].astype(np.uint64)
        out |= np.where(shift > 0, b8 << (np.uint64(64) - shift), np.uint64(0))
    wu = w.astype(np.uint64)
    mask = np.where(
        wu >= 64, np.uint64(0xFFFFFFFFFFFFFFFF),
        (np.uint64(1) << wu) - np.uint64(1),
    )
    deltas = (out & mask) + mins[m]
    # wrap-around cumulative sum in unsigned target-width arithmetic
    acc2 = np.empty(total, dtype=np.uint64)
    acc2[0] = np.uint64(first & 0xFFFFFFFFFFFFFFFF)
    np.cumsum(deltas, out=acc2[1:])
    acc2[1:] += acc2[0]
    if bits == 32:
        return acc2.astype(np.uint32).astype(np.int32), pos
    return acc2.astype(np.int64), pos


def encode(
    values: np.ndarray,
    bits: int = 64,
    block_size: int = 128,
    minis_per_block: int = 4,
) -> bytes:
    """Encode int values as DELTA_BINARY_PACKED (reference writer geometry)."""
    vals = np.asarray(values)
    total = len(vals)
    out = bytearray()

    def put_uvarint(v: int) -> None:
        while v >= 0x80:
            out.append((v & 0x7F) | 0x80)
            v >>= 7
        out.append(v)

    def put_zigzag(v: int) -> None:
        if bits == 32:
            put_uvarint(((v << 1) ^ (v >> 31)) & 0xFFFFFFFF)
        else:
            put_uvarint(((v << 1) ^ (v >> 63)) & 0xFFFFFFFFFFFFFFFF)

    put_uvarint(block_size)
    put_uvarint(minis_per_block)
    put_uvarint(total)
    first = int(vals[0]) if total else 0
    put_zigzag(first)
    if total <= 1:
        return bytes(out)

    mask = np.uint64(0xFFFFFFFF if bits == 32 else 0xFFFFFFFFFFFFFFFF)
    u = vals.astype(np.uint64) & mask
    deltas = (u[1:] - u[:-1]) & mask  # wrapping diff in target width
    # interpret as signed target-width for min-delta selection
    if bits == 32:
        sdeltas = deltas.astype(np.uint32).astype(np.int32).astype(np.int64)
    else:
        sdeltas = deltas.astype(np.int64)

    values_per_mini = block_size // minis_per_block
    n = len(deltas)
    for b0 in range(0, n, block_size):
        block = sdeltas[b0 : b0 + block_size]
        min_delta = int(block.min())
        put_zigzag(min_delta)
        # adjusted deltas are guaranteed non-negative in target-width arithmetic
        adj = (block.astype(np.uint64) - np.uint64(min_delta & int(mask))) & mask
        nminis = (len(block) + values_per_mini - 1) // values_per_mini
        widths = []
        chunks = []
        for m in range(minis_per_block):
            lo = m * values_per_mini
            if m < nminis:
                chunk = adj[lo : lo + values_per_mini]
                w = int(chunk.max()).bit_length() if len(chunk) else 0
                widths.append(w)
                chunks.append(chunk)
            else:
                widths.append(0)
                chunks.append(None)
        out.extend(bytes(widths))
        for m in range(nminis):
            chunk = chunks[m]
            if chunk is None or widths[m] == 0:
                continue
            if len(chunk) < values_per_mini:
                chunk = np.concatenate(
                    [chunk, np.zeros(values_per_mini - len(chunk), dtype=np.uint64)]
                )
            out.extend(bitpack.pack(chunk, widths[m]))
    return bytes(out)
