"""Decode/encode kernels for the hot parquet paths.

Each kernel exists as a vectorized NumPy host implementation (the correctness
reference, and the host fallback) and — for the decode hot path — a JAX/XLA device
implementation in jax_kernels.py used by the TPU pipeline.  This replaces the
reference's per-value virtual-dispatch decoders (hybrid_decoder.go, deltabp_decoder.go,
type_*.go) with batch-oriented array transforms (SURVEY.md §7.1).
"""
