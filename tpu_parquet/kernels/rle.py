"""RLE / bit-packed hybrid codec (parquet `RLE` encoding).

Used for definition/repetition levels, dictionary indices, and boolean RLE.
Wire format (hybrid_decoder.go:29-165 semantics):

    stream      := [uint32 little-endian length prefix]? run*
    run header  := uvarint h
    h & 1 == 1  : bit-packed run of (h >> 1) groups of 8 values, ``width`` bits each
    h & 1 == 0  : RLE run — one value stored in ceil(width/8) LE bytes, repeated
                  (h >> 1) times

The reference decodes value-at-a-time through interface calls; here the run
structure is parsed on the host (cheap, metadata-sized) and runs are expanded with
vectorized repeat/unpack — the decomposition SURVEY.md §7.2-P2 prescribes so the
bulky expansion can also run on device with static shapes.
"""

from __future__ import annotations

from ..errors import ParquetError

import io
from dataclasses import dataclass

import numpy as np

from . import bitpack

__all__ = ["decode", "encode", "decode_prefixed", "parse_runs", "RunList"]


class RLEError(ParquetError):
    pass


@dataclass
class RunList:
    """Parsed run structure of a hybrid stream (host-side metadata)."""

    # Per run: kind 0=RLE, 1=bit-packed
    kinds: list
    # RLE: the repeated value and count; BP: numpy array of unpacked values
    payloads: list
    total: int


def _read_uvarint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    n = len(buf)
    while True:
        if pos >= n:
            raise RLEError("truncated run header varint")
        b = int(buf[pos])  # int(): numpy uint8 would wrap under << shift
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise RLEError("run header varint too long")


def parse_runs(buf: bytes, width: int, count: int) -> RunList:
    """Parse run headers and expand per-run payloads until ``count`` values."""
    if width < 0 or width > 64:
        raise RLEError(f"invalid bit width {width}")
    kinds: list = []
    payloads: list = []
    total = 0
    pos = 0
    value_bytes = (width + 7) // 8
    n = len(buf)
    while total < count:
        if pos >= n:
            raise RLEError(
                f"hybrid stream exhausted: wanted {count} values, got {total}"
            )
        h, pos = _read_uvarint(buf, pos)
        if h & 1:  # bit-packed run: (h>>1) groups of 8
            groups = h >> 1
            nvals = groups * 8
            if nvals == 0:
                continue
            nbytes = groups * width
            if pos + nbytes > n:
                raise RLEError("truncated bit-packed run")
            # don't unpack groups beyond what the caller needs (bounded blowup:
            # a huge group count already failed the buffer check above, but a
            # stream can still legitimately hold trailing groups we don't want)
            need_groups = (count - total + 7) // 8
            nvals = min(nvals, need_groups * 8)
            vals = bitpack.unpack(
                np.frombuffer(buf, np.uint8, min(nbytes, need_groups * width), pos),
                width,
                nvals,
            )
            pos += nbytes
            kinds.append(1)
            payloads.append(vals)
            total += nvals
        else:  # RLE run
            repeats = h >> 1
            if repeats == 0:
                continue
            # clamp to what the caller asked for: a malicious header can claim
            # 2^50 repeats from a few bytes of input — never materialize more
            # than `count` values from it
            repeats = min(repeats, count - total)
            if pos + value_bytes > n:
                raise RLEError("truncated RLE run value")
            v = int.from_bytes(buf[pos : pos + value_bytes], "little") if value_bytes else 0
            pos += value_bytes
            kinds.append(0)
            payloads.append((v, repeats))
            total += repeats
    return RunList(kinds=kinds, payloads=payloads, total=total)


def _decode_native(buf: bytes, width: int, count: int) -> "np.ndarray | None":
    """Whole-stream vectorized decode: native C run walk + one numpy pass.

    The per-run loop in :func:`decode` is the host hot spot on level-heavy
    nested files (pyarrow emits one bit-packed run per ~504 values, so a 1M-row
    page costs ~2000 Python iterations + unpack calls).  This path mirrors the
    device kernel instead: the C walker emits (ends, is_rle, values,
    bit_starts) run tables, then every output position gathers its field in one
    vectorized sweep — searchsorted for the run, byte-window gather + shift +
    mask for bit-packed positions.  Returns None when the native library is
    unavailable or the width needs >32 bits (the loop handles those).
    """
    if width > 32 or count == 0:
        return None
    from .. import native

    if not isinstance(buf, bytes):
        buf = bytes(buf)
    res = native.hybrid_meta_retry(buf, len(buf), 0, width, count)
    if res is None:
        return None
    if isinstance(res, int):
        if res == -10:
            return None
        raise RLEError(
            native.NATIVE_ERRORS.get(res, f"hybrid parse error {res}")
        )
    n_runs, _consumed, ends, kinds, vals, starts = res[:6]
    if width == 0:
        return np.zeros(count, dtype=np.uint32)
    # C expansion first: same run-table contract, one pass, GIL released
    # (the numpy sweep below is the fallback and the fuzz-parity oracle)
    expanded = native.hybrid_expand(buf, ends[:n_runs], kinds[:n_runs],
                                    vals[:n_runs], starts[:n_runs],
                                    width, count)
    if expanded is not None:
        return expanded
    i = np.arange(count, dtype=np.int64)
    r = np.searchsorted(ends, i, side="right")
    r = np.minimum(r, n_runs - 1)
    is_bp = kinds[r] == 0
    bit = starts[r] + i * width  # starts are pre-normalized by -run_start*width
    bit = np.where(is_bp, bit, 0)  # RLE rows: don't let fake offsets run OOB
    byte0 = bit >> 3
    shift = (bit & 7).astype(np.uint64)
    nbytes = (width + 7 + 7) // 8  # field + worst-case shift, <= 5 for w<=32
    data = np.frombuffer(buf, dtype=np.uint8)
    padded = np.zeros(len(data) + 8, dtype=np.uint8)
    padded[: len(data)] = data
    acc = np.zeros(count, dtype=np.uint64)
    for k in range(nbytes):
        acc |= padded[byte0 + k].astype(np.uint64) << np.uint64(8 * k)
    mask = np.uint64((1 << width) - 1)
    extracted = ((acc >> shift) & mask).astype(np.uint32)
    return np.where(is_bp, extracted, vals[r].astype(np.uint32))


def decode(buf: bytes, width: int, count: int) -> np.ndarray:
    """Decode exactly ``count`` values from a hybrid stream (no length prefix)."""
    out_dtype = np.uint32 if width <= 32 else np.uint64
    if count == 0:
        return np.zeros(0, dtype=out_dtype)
    fast = _decode_native(buf, width, count)
    if fast is not None:
        return fast
    runs = parse_runs(buf, width, count)
    parts = []
    for kind, payload in zip(runs.kinds, runs.payloads):
        if kind == 0:
            v, repeats = payload
            parts.append(np.full(repeats, v, dtype=out_dtype))
        else:
            parts.append(payload.astype(out_dtype, copy=False))
    out = parts[0] if len(parts) == 1 else np.concatenate(parts)
    # bit-packed runs pad to 8; trim any trailing padding
    return out[:count]


def decode_prefixed(buf: bytes, width: int, count: int) -> tuple[np.ndarray, int]:
    """Decode a v1-style stream with a uint32 LE length prefix.

    Returns (values, bytes_consumed_including_prefix) — the level-stream layout of
    data page v1 (page_v1.go:113-119 `initSize` path).
    """
    if len(buf) < 4:
        raise RLEError("truncated level stream: missing length prefix")
    size = int.from_bytes(buf[:4], "little")
    if 4 + size > len(buf):
        raise RLEError(f"level stream length {size} exceeds buffer {len(buf) - 4}")
    return decode(buf[4 : 4 + size], width, count), 4 + size


# ---------------------------------------------------------------------------
# Encoder
# ---------------------------------------------------------------------------

def encode(
    values: np.ndarray, width: int, *, use_rle_runs: bool = True, min_rle_run: int = 8
) -> bytes:
    """Encode values as a hybrid stream.

    Unlike the reference writer — which only ever emits bit-packed runs
    (hybrid_encoder.go:9-109, README.md:42) — long constant stretches are emitted
    as true RLE runs when ``use_rle_runs`` (both forms are spec-valid; RLE runs are
    strictly smaller for constant data such as all-defined def levels).  Setting
    ``use_rle_runs=False`` reproduces the reference's bit-packed-only behaviour.
    """
    vals = np.asarray(values, dtype=np.uint64)
    n = len(vals)
    out = bytearray()
    value_bytes = (width + 7) // 8

    def put_uvarint(v: int) -> None:
        while v >= 0x80:
            out.append((v & 0x7F) | 0x80)
            v >>= 7
        out.append(v)

    def put_bitpacked(chunk: np.ndarray) -> None:
        pad = (-len(chunk)) % 8
        if pad:
            chunk = np.concatenate([chunk, np.zeros(pad, dtype=np.uint64)])
        groups = len(chunk) // 8
        put_uvarint((groups << 1) | 1)
        out.extend(bitpack.pack(chunk, width))

    def put_rle(value: int, repeats: int) -> None:
        put_uvarint(repeats << 1)
        out.extend(int(value).to_bytes(value_bytes, "little"))

    if n == 0:
        return bytes(out)
    if width == 0:
        # all values are zero-width: a single RLE run carries the count
        put_uvarint(n << 1)
        return bytes(out)

    if not use_rle_runs:
        put_bitpacked(vals)
        return bytes(out)

    # Segment into constant runs; emit RLE for long runs, bit-packed spans between.
    # A mid-stream bit-packed run always decodes to exactly 8*groups values, so any
    # span we bit-pack before an RLE run must hold a multiple of 8 real values —
    # we borrow leading repeats from the constant run to reach alignment (they are
    # constant, so moving them into the bit-packed span is value-preserving).
    # Only the final bit-packed run may be zero-padded (decoder trims by count).
    change = np.flatnonzero(np.diff(vals)) + 1
    bounds = np.concatenate([[0], change, [n]])
    run_starts = bounds[:-1]
    run_lens = np.diff(bounds)
    min_rle = max(min_rle_run, 8)
    # only constant runs >= min_rle can become RLE; everything else stays in
    # the buffered bit-packed span — iterating candidates (few) instead of
    # every segment (~n for high-cardinality data) keeps this O(runs_emitted)
    pending_start = 0  # start of accumulated not-yet-emitted span
    for ci in np.flatnonzero(run_lens >= min_rle):
        start = int(run_starts[ci])
        run_len = int(run_lens[ci])
        pend = start - pending_start
        borrow = (-pend) % 8
        if run_len - borrow < min_rle:
            continue  # borrowing for alignment would gut the run; keep buffering
        if pend + borrow:
            put_bitpacked(vals[pending_start : start + borrow])
        put_rle(int(vals[start]), run_len - borrow)
        pending_start = start + run_len
    if n > pending_start:
        put_bitpacked(vals[pending_start:])
    return bytes(out)


def encode_prefixed(values: np.ndarray, width: int, **kw) -> bytes:
    """Hybrid stream with the uint32 length prefix used by v1 level streams."""
    body = encode(values, width, **kw)
    return len(body).to_bytes(4, "little") + body
