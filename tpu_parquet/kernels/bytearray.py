"""Delta byte-array codecs: DELTA_LENGTH_BYTE_ARRAY and DELTA_BYTE_ARRAY.

DELTA_LENGTH_BYTE_ARRAY (type_bytearray.go:98-187 semantics): a DELTA_BINARY_PACKED
stream of value lengths, then all value bytes concatenated.  Decode is a cumsum of
lengths — offsets fall straight out.

DELTA_BYTE_ARRAY (type_bytearray.go:189-292): two delta streams — shared-prefix
lengths and suffix lengths — then concatenated suffix bytes.  Each value reuses a
prefix of its *predecessor*, which is inherently sequential; the stitch runs on the
host with numpy (SURVEY.md §7.4.4 hard-part ranking).
"""

from __future__ import annotations

from ..errors import ParquetError

import numpy as np

from ..column import ByteArrayData
from . import delta

__all__ = [
    "decode_delta_length",
    "encode_delta_length",
    "decode_delta",
    "encode_delta",
]


class ByteArrayError(ParquetError):
    pass


def decode_delta_length(buf: bytes, count: int) -> ByteArrayData:
    lens, consumed = delta.decode(buf, bits=64)
    if len(lens) < count:
        raise ByteArrayError(
            f"DELTA_LENGTH_BYTE_ARRAY: {len(lens)} lengths for {count} values"
        )
    lens = lens[:count]
    if np.any(lens < 0):
        raise ByteArrayError("negative value length")
    offsets = np.zeros(count + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    total = int(offsets[-1])
    if consumed + total > len(buf):
        raise ByteArrayError(
            f"DELTA_LENGTH_BYTE_ARRAY: needs {total} payload bytes, have {len(buf) - consumed}"
        )
    heap = np.frombuffer(buf, np.uint8, total, consumed).copy()
    return ByteArrayData(offsets=offsets, heap=heap)


def encode_delta_length(ba: ByteArrayData) -> bytes:
    lens = (ba.offsets[1:] - ba.offsets[:-1]).astype(np.int64)
    return delta.encode(lens, bits=64) + ba.heap.tobytes()


def decode_delta(buf: bytes, count: int) -> ByteArrayData:
    """DELTA_BYTE_ARRAY: prefix lengths + suffix stream with incremental reuse."""
    prefix_lens, consumed = delta.decode(buf, bits=64)
    if len(prefix_lens) < count:
        raise ByteArrayError("DELTA_BYTE_ARRAY: short prefix-length stream")
    prefix_lens = prefix_lens[:count]
    if np.any(prefix_lens < 0):
        raise ByteArrayError("negative prefix length")
    suffixes = decode_delta_length(buf[consumed:], count)
    if count == 0:
        return suffixes
    if int(prefix_lens[0]) != 0:
        raise ByteArrayError("first value cannot have a prefix")

    suf_lens = suffixes.offsets[1:] - suffixes.offsets[:-1]
    out_lens = prefix_lens + suf_lens
    out_offsets = np.zeros(count + 1, dtype=np.int64)
    np.cumsum(out_lens, out=out_offsets[1:])
    heap = np.empty(int(out_offsets[-1]), dtype=np.uint8)
    prev_start = 0
    prev_len = 0
    s_off = suffixes.offsets
    s_heap = suffixes.heap
    from .. import native

    rc = native.delta_ba_stitch(
        np.ascontiguousarray(prefix_lens, dtype=np.int64),
        np.ascontiguousarray(s_off, dtype=np.int64),
        np.ascontiguousarray(s_heap, dtype=np.uint8),
        out_offsets,
        heap,
    )
    if rc == 0:
        return ByteArrayData(offsets=out_offsets, heap=heap)
    if rc == -30:
        raise ByteArrayError("prefix longer than previous value")
    # native unavailable: reference Python chain below
    for i in range(count):
        p = int(prefix_lens[i])
        if p > prev_len:
            raise ByteArrayError(
                f"value {i}: prefix {p} longer than previous value {prev_len}"
            )
        start = int(out_offsets[i])
        if p:
            heap[start : start + p] = heap[prev_start : prev_start + p]
        sl = int(suf_lens[i])
        if sl:
            heap[start + p : start + p + sl] = s_heap[s_off[i] : s_off[i] + sl]
        prev_start = start
        prev_len = p + sl
    return ByteArrayData(offsets=out_offsets, heap=heap)


def encode_delta(ba: ByteArrayData) -> bytes:
    """Compute shared prefixes vs the previous value, emit the two delta streams."""
    n = len(ba)
    prefix_lens = np.zeros(n, dtype=np.int64)
    heap = ba.heap
    off = ba.offsets
    for i in range(1, n):
        a0, a1 = int(off[i - 1]), int(off[i])
        b0, b1 = int(off[i]), int(off[i + 1])
        max_p = min(a1 - a0, b1 - b0)
        if max_p:
            av = heap[a0 : a0 + max_p]
            bv = heap[b0 : b0 + max_p]
            neq = np.flatnonzero(av != bv)
            prefix_lens[i] = int(neq[0]) if len(neq) else max_p
    # suffixes
    suf_parts = []
    suf_lens = np.empty(n, dtype=np.int64)
    for i in range(n):
        s0 = int(off[i]) + int(prefix_lens[i])
        s1 = int(off[i + 1])
        suf_lens[i] = s1 - s0
        suf_parts.append(heap[s0:s1])
    suf_offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(suf_lens, out=suf_offsets[1:])
    suf_heap = (
        np.concatenate(suf_parts) if suf_parts else np.zeros(0, dtype=np.uint8)
    )
    suffixes = ByteArrayData(offsets=suf_offsets, heap=suf_heap)
    return delta.encode(prefix_lens, bits=64) + encode_delta_length(suffixes)
