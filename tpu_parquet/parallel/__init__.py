"""Multi-chip / multi-host parallel decode (SPMD over a jax.sharding.Mesh).

The reference is strictly single-threaded value-at-a-time (TODO.md:15 — the
reader is not concurrent); its natural block hierarchy (file → row group →
column chunk → page, SURVEY.md §5.7) is what this module turns into parallel
axes:

- **pages** of identical geometry batch under ``vmap`` and shard over the mesh's
  ``data`` axis with ``shard_map`` — each device decodes its slice of the page
  batch, and cross-device reductions (global stats) ride ICI collectives
  (``psum``/``pmin``/``pmax``), never the host;
- **row groups** are embarrassingly parallel and are *assigned*, not exchanged:
  a greedy LPT plan balances compressed bytes across shards (hosts or chips) —
  the §5.8 stance that the decode path needs sharded work lists, not an
  NCCL-analog exchange;
- **multi-host**: each process decodes the row groups its shard owns;
  ``jax.make_array_from_process_local_data`` assembles the global sharded array
  view when a training step consumes the columns.

Everything compiles once per page geometry: within a mesh the per-device page
count is static, so the same executable serves every batch of that shape.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..format import Type
from .. import jax_kernels as K
from ..jax_kernels import scoped_x64
from ..jax_decode import HybridMeta, DeltaMeta, parse_hybrid_meta, parse_delta_meta, _bucket, _SLACK

# shard_map moved and renamed a kwarg across jax releases: newer jax exposes
# ``jax.shard_map(..., check_vma=)``, 0.4.x only
# ``jax.experimental.shard_map.shard_map(..., check_rep=)``.  Resolve once.
if hasattr(jax, "shard_map"):
    def _shard_map(fn, mesh, in_specs, out_specs):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
else:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map(fn, mesh, in_specs, out_specs):
        return _exp_shard_map(fn, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)

__all__ = [
    "make_mesh",
    "plan_shards",
    "process_shard",
    "shard_scan_row_groups",
    "PageBatch",
    "pack_hybrid_pages",
    "pack_delta_pages",
    "sharded_dict_decode",
    "sharded_dict_decode_2d",
    "sharded_delta_decode",
    "sharded_plain_decode",
    "column_stats",
    "shard_row_ranges",
    "decode_row_span",
    "global_column_array",
    "process_local_column",
]


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None, axis: str = "data"
) -> Mesh:
    """1-D data mesh over all (or given) devices — the decode path needs no
    model axis; re-sharding decoded columns onto a 2-D mesh is the consumer's
    pjit's job (XLA inserts the all-to-all)."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs, (axis,))


# ---------------------------------------------------------------------------
# Work-list sharding (row groups → shards)
# ---------------------------------------------------------------------------

def plan_shards(sizes: Sequence[int], n_shards: int) -> list[list[int]]:
    """Greedy LPT assignment of row groups to shards, balanced by byte size.

    ``sizes[i]`` is row group i's total_compressed_size (or total_byte_size).
    Returns per-shard lists of row-group indices.  Deterministic, so every
    host computes the identical plan from the shared footer — no coordination
    traffic (DCN only ships the footer, per SURVEY.md §5.8).
    """
    if n_shards <= 0:
        raise ValueError(f"n_shards must be positive, got {n_shards}")
    order = sorted(range(len(sizes)), key=lambda i: -int(sizes[i]))
    loads = [0] * n_shards
    plan: list[list[int]] = [[] for _ in range(n_shards)]
    for i in order:
        s = loads.index(min(loads))
        plan[s].append(i)
        loads[s] += int(sizes[i])
    for shard in plan:
        shard.sort()
    return plan


def process_shard() -> tuple[int, int]:
    """This process's ``(shard_index, n_shards)`` under ``jax.distributed``.

    The shard tuple ``data.DataLoader`` (and any plan_shards caller) wants on
    a multi-host job: every host derives the identical LPT plan from the
    shared footers, so the only coordination is jax.distributed's own
    process enumeration.  On a single-process runtime this is ``(0, 1)`` —
    the same code serves tests and clusters.
    """
    return int(jax.process_index()), int(jax.process_count())


def _reader_prefetch(reader) -> int:
    """A reader's configured pipeline depth: FileReader exposes ``prefetch``,
    DeviceFileReader ``_prefetch``; any other reader defaults to 0."""
    return int(getattr(reader, "prefetch", None)
               or getattr(reader, "_prefetch", 0) or 0)


def shard_scan_row_groups(reader, shard_index: int, n_shards: int,
                          prefetch: Optional[int] = None):
    """Decode the row groups LPT-assigned to ``shard_index``, pipelined.

    The per-SHARD pipeline form of the work-list split: every shard computes
    the identical byte-balanced plan from the shared footer (plan_shards —
    no coordination traffic) and decodes only its own groups, each through
    the reader's overlapped chunk pipeline (``prefetch`` per-call override;
    the reader's own setting otherwise).  Shards run in different
    processes/hosts, so pipelines are deliberately per-shard rather than
    one global pool.  Yields ``(row_group_index, {column: ColumnData})``.
    """
    sizes = [
        sum(cc.meta_data.total_compressed_size or 0
            for cc in (rg.columns or []) if cc.meta_data is not None)
        for rg in reader.metadata.row_groups
    ]
    plan = plan_shards(sizes, n_shards)
    if not 0 <= shard_index < n_shards:
        raise ValueError(f"shard {shard_index} of {n_shards}")
    mine = plan[shard_index]
    k = _reader_prefetch(reader) if prefetch is None else int(prefetch)
    if k > 0 and hasattr(reader, "_decode_row_groups"):
        # ONE pipeline over the whole shard: the window spans group
        # boundaries (per-group read_row_group calls would build and drain
        # a pool at every boundary — exactly the stall this exists to hide)
        yield from reader._decode_row_groups(mine, k)
        return
    for i in mine:
        # bare call: the generic reader contract (a DeviceFileReader's
        # read_row_group has no prefetch kwarg)
        yield i, reader.read_row_group(i)


# ---------------------------------------------------------------------------
# Page batching: N same-geometry pages → stacked device arrays
# ---------------------------------------------------------------------------

@dataclass
class PageBatch:
    """A batch of same-geometry encoded pages, stacked for vmap/shard_map.

    ``bufs`` u8[B, S]: padded page bytes.  Hybrid (dictionary-index) batches
    carry run tables [B, R]; delta batches carry miniblock tables [B, M].
    ``count`` values per page is uniform; a short tail page is padded with a
    synthetic zero run via pack_hybrid_pages(counts=...) and callers slice the
    decoded tail back (delta batches require equal counts — pack_delta_pages
    raises otherwise).
    """

    bufs: jax.Array
    count: int
    width: int = 0                      # hybrid: index bit width
    run_ends: Optional[jax.Array] = None
    run_is_rle: Optional[jax.Array] = None
    run_values: Optional[jax.Array] = None
    run_bit_starts: Optional[jax.Array] = None
    first_values: Optional[jax.Array] = None    # delta: per-page seed
    mini_bit_starts: Optional[jax.Array] = None
    mini_widths: Optional[jax.Array] = None
    mini_min_delta: Optional[jax.Array] = None
    values_per_mini: int = 0
    max_width: int = 0

    @property
    def num_pages(self) -> int:
        return int(self.bufs.shape[0])


def _stack_padded_bufs(raws: list[bytes]) -> np.ndarray:
    size = _bucket(max(len(r) for r in raws) + _SLACK, 64)
    out = np.zeros((len(raws), size), dtype=np.uint8)
    for i, r in enumerate(raws):
        out[i, : len(r)] = np.frombuffer(r, dtype=np.uint8)
    return out


@scoped_x64
def pack_hybrid_pages(
    raws: list[bytes],
    width: int,
    count: int,
    pos: int = 0,
    counts: Optional[Sequence[int]] = None,
) -> PageBatch:
    """Parse + stack N hybrid (RLE/bit-packed) streams of ``count`` values each.

    ``counts`` gives per-page actual value counts when they differ (the usual
    short tail page): shorter pages are padded to ``count`` with a synthetic
    zero-value RLE run, and callers slice the decoded tail back to its real
    length.  Host cost is O(total run headers); run tables are padded to the
    batch max (power-of-two bucketed) so one executable serves all batches of
    this shape.
    """
    per_page = list(counts) if counts is not None else [count] * len(raws)
    if len(per_page) != len(raws):
        raise ValueError(f"{len(per_page)} counts for {len(raws)} pages")
    if any(c > count for c in per_page):
        raise ValueError(f"page count exceeds batch count {count}")
    metas = [
        parse_hybrid_meta(r, width, c, pos=pos) for r, c in zip(raws, per_page)
    ]
    for m, c in zip(metas, per_page):
        if c < count:  # pad: one RLE run of zeros fills the tail
            # run_ends stays sorted: real runs end ≤ c, bucket padding == c,
            # the synthetic run == count, so searchsorted routes tail slots here
            m.run_ends = np.concatenate([m.run_ends, [count]]).astype(np.int64)
            m.run_is_rle = np.concatenate([m.run_is_rle, [True]])
            m.run_values = np.concatenate([m.run_values, [0]]).astype(np.uint32)
            m.run_bit_starts = np.concatenate([m.run_bit_starts, [0]]).astype(np.int64)
    r_max = max(m.run_ends.shape[0] for m in metas)
    ends = np.full((len(metas), r_max), count, dtype=np.int64)
    is_rle = np.zeros((len(metas), r_max), dtype=bool)
    vals = np.zeros((len(metas), r_max), dtype=np.uint32)
    starts = np.zeros((len(metas), r_max), dtype=np.int64)
    for i, m in enumerate(metas):
        r = m.run_ends.shape[0]
        ends[i, :r] = m.run_ends
        is_rle[i, :r] = m.run_is_rle
        vals[i, :r] = m.run_values
        starts[i, :r] = m.run_bit_starts
    return PageBatch(
        bufs=jnp.asarray(_stack_padded_bufs(raws)),
        count=count,
        width=width,
        run_ends=jnp.asarray(ends),
        run_is_rle=jnp.asarray(is_rle),
        run_values=jnp.asarray(vals),
        run_bit_starts=jnp.asarray(starts),
    )


@scoped_x64
def pack_delta_pages(raws: list[bytes], bits: int, count: int) -> PageBatch:
    """Parse + stack N DELTA_BINARY_PACKED streams of ``count`` values each."""
    metas = [parse_delta_meta(r, bits) for r in raws]
    for m in metas:
        if m.count != count:
            raise ValueError(f"page holds {m.count} values, batch expects {count}")
    m_max = max(m.mini_bit_starts.shape[0] for m in metas)
    starts = np.zeros((len(metas), m_max), dtype=np.int64)
    widths = np.zeros((len(metas), m_max), dtype=np.int32)
    mins = np.zeros((len(metas), m_max), dtype=np.uint64)
    firsts = np.zeros(len(metas), dtype=np.int64)
    for i, m in enumerate(metas):
        k = m.mini_bit_starts.shape[0]
        starts[i, :k] = m.mini_bit_starts
        widths[i, :k] = m.mini_widths
        mins[i, :k] = m.mini_min_delta
        firsts[i] = m.first_value
    return PageBatch(
        bufs=jnp.asarray(_stack_padded_bufs(raws)),
        count=count,
        first_values=jnp.asarray(firsts),
        mini_bit_starts=jnp.asarray(starts),
        mini_widths=jnp.asarray(widths),
        mini_min_delta=jnp.asarray(mins),
        values_per_mini=metas[0].values_per_mini,
        max_width=max(1, *(int(m.mini_widths.max(initial=0)) for m in metas)),
    )


# ---------------------------------------------------------------------------
# Sharded decode steps (shard_map over the data axis)
# ---------------------------------------------------------------------------

@scoped_x64
def sharded_dict_decode(
    batch: PageBatch, dict_u8: jax.Array, dtype: str, mesh: Mesh,
    axis: str = "data", with_stats: bool = False,
):
    """Decode a batch of dictionary-index pages and gather values, sharded.

    Pages shard across ``axis``; the dictionary replicates (it is per-chunk and
    small — ≤ 32767 entries by the format's own fallback rule).  Returns the
    decoded values [B, count, ...] with the same sharding, so a downstream pjit
    consumes them without a host round-trip; XLA inserts any re-shard
    collectives.  ``with_stats`` adds a psum/pmin/pmax over ICI — the global
    column statistics every shard sees identically.
    """
    width, count = batch.width, batch.count

    def shard_fn(bufs, ends, is_rle, vals, starts, d_u8):
        idx = jax.vmap(
            lambda b, e, r, v, s: K.expand_rle_hybrid(b, e, r, v, s, width, count)
        )(bufs, ends, is_rle, vals, starts)
        flat = K.dict_gather_bytes(d_u8, idx.reshape(-1), dtype)
        out = flat.reshape(idx.shape + flat.shape[1:])
        if not with_stats:
            return out, jnp.zeros(3, dtype=jnp.int64)
        stats = jnp.stack([
            jax.lax.psum(jnp.int64(idx.size), axis),
            jax.lax.pmin(jnp.min(idx).astype(jnp.int64), axis),
            jax.lax.pmax(jnp.max(idx).astype(jnp.int64), axis),
        ])
        return out, stats

    fn = _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis, None), P(axis, None),
                  P(axis, None), P(None, None)),
        out_specs=(P(axis, None), P()),
    )
    return fn(
        batch.bufs, batch.run_ends, batch.run_is_rle, batch.run_values,
        batch.run_bit_starts, dict_u8,
    )


@scoped_x64
def sharded_dict_decode_2d(
    batch: PageBatch, dict_u8: jax.Array, dtype: str, mesh: Mesh,
    data_axis: str = "data", model_axis: str = "model",
):
    """Dict decode on a 2-D mesh: pages shard over ``data``, the *dictionary*
    shards over ``model`` — the expert-parallel-shaped variant for dictionaries
    too large to replicate.

    Each device gathers only the indices that fall in its dictionary shard
    (masked local gather) and a psum over ``model`` assembles full values: the
    index-routing pattern of MoE dispatch, with the reduction riding ICI.
    Requires an integer ``dtype`` (psum assembles words exactly; float dicts
    replicate via :func:`sharded_dict_decode` instead).
    """
    width, count = batch.width, batch.count
    n_model = mesh.shape[model_axis]
    k = int(dict_u8.shape[0])
    shard_rows = (k + n_model - 1) // n_model
    pad_rows = shard_rows * n_model - k
    if pad_rows:
        dict_u8 = jnp.concatenate(
            [dict_u8, jnp.zeros((pad_rows, dict_u8.shape[1]), dtype=jnp.uint8)]
        )

    def shard_fn(bufs, ends, is_rle, vals, starts, d_u8_local):
        m = jax.lax.axis_index(model_axis)
        lo = m.astype(jnp.int64) * shard_rows
        idx = jax.vmap(
            lambda b, e, r, v, s: K.expand_rle_hybrid(b, e, r, v, s, width, count)
        )(bufs, ends, is_rle, vals, starts)
        flat = idx.reshape(-1).astype(jnp.int64)
        local = flat - lo
        mine = (local >= 0) & (local < shard_rows)
        safe = jnp.clip(local, 0, shard_rows - 1).astype(jnp.int32)
        gathered = K.dict_gather_bytes(d_u8_local, safe, dtype)
        gathered = jnp.where(
            mine.reshape(mine.shape + (1,) * (gathered.ndim - 1)),
            gathered,
            jnp.zeros((), dtype=gathered.dtype),
        )
        full = jax.lax.psum(gathered, model_axis)
        return full.reshape(idx.shape + full.shape[1:])

    fn = _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(data_axis, None), P(data_axis, None), P(data_axis, None),
                  P(data_axis, None), P(data_axis, None), P(model_axis, None)),
        out_specs=P(data_axis, None),
    )
    return fn(
        batch.bufs, batch.run_ends, batch.run_is_rle, batch.run_values,
        batch.run_bit_starts, dict_u8,
    )


@scoped_x64
def sharded_delta_decode(
    batch: PageBatch, bits: int, mesh: Mesh, axis: str = "data",
):
    """Decode a batch of DELTA_BINARY_PACKED pages, sharded over the mesh."""
    count = batch.count
    vpm, mw = batch.values_per_mini, batch.max_width

    def shard_fn(bufs, firsts, starts, widths, mins):
        return jax.vmap(
            lambda b, f, s, w, m: K.delta_reconstruct(
                b, f, s, w, m, vpm, count, bits, mw
            )
        )(bufs, firsts, starts, widths, mins)

    fn = _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis), P(axis, None), P(axis, None),
                  P(axis, None)),
        out_specs=P(axis, None),
    )
    return fn(
        batch.bufs, batch.first_values, batch.mini_bit_starts,
        batch.mini_widths, batch.mini_min_delta,
    )


@scoped_x64
def sharded_plain_decode(
    bufs: jax.Array, dtype: str, count: int, mesh: Mesh, axis: str = "data",
):
    """PLAIN fixed-width pages [B, S] → values [B, count], sharded bitcast."""

    def shard_fn(b):
        return jax.vmap(lambda x: K.plain_decode_fixed(x, dtype, count))(b)

    fn = _shard_map(
        shard_fn, mesh=mesh, in_specs=(P(axis, None),),
        out_specs=P(axis, None),
    )
    return fn(bufs)


@scoped_x64
def column_stats(values: jax.Array, mesh: Mesh, axis: str = "data"):
    """Global min/max/count over a sharded int column — one ICI reduction.

    The device-side analog of the reference's write-side stats trackers
    (stats.go): every shard computes local extrema, psum/pmin/pmax make them
    global without gathering the data anywhere.
    """

    def shard_fn(v):
        return jnp.stack([
            jax.lax.psum(jnp.int64(v.size), axis),
            jax.lax.pmin(jnp.min(v).astype(jnp.int64), axis),
            jax.lax.pmax(jnp.max(v).astype(jnp.int64), axis),
        ])

    fn = _shard_map(
        shard_fn, mesh=mesh, in_specs=(P(axis, None),), out_specs=P(),
    )
    return fn(values)


# ---------------------------------------------------------------------------
# Multi-host work list → global sharded array (SURVEY.md §5.8)
# ---------------------------------------------------------------------------

def shard_row_ranges(total_rows: int, n_shards: int) -> list[tuple[int, int]]:
    """Contiguous, equal-size row spans, one per shard (last may be short).

    Equal spans are what a NamedSharding over the row axis requires; each
    shard decodes only the row groups its span touches (boundary groups are
    decoded by both neighbors and sliced — the standard input-pipeline trade
    against cross-host exchange).  Deterministic from (total_rows, n_shards),
    so every host derives the identical plan from the footer alone — DCN
    carries no work-list coordination, matching SURVEY.md §5.8.
    """
    if n_shards <= 0:
        raise ValueError(f"n_shards must be positive, got {n_shards}")
    per = -(-total_rows // n_shards) if total_rows else 0
    return [
        (min(i * per, total_rows), min((i + 1) * per, total_rows))
        for i in range(n_shards)
    ]


_FIXED_DTYPES = {
    Type.INT32: np.dtype(np.int32),
    Type.INT64: np.dtype(np.int64),
    Type.FLOAT: np.dtype(np.float32),
    Type.DOUBLE: np.dtype(np.float64),
    Type.BOOLEAN: np.dtype(bool),
}


def column_span_dtype(reader, column: str) -> np.dtype:
    """The numpy dtype a flat column decodes to — derivable from the schema
    alone, so shards with EMPTY spans pad with the right dtype without
    decoding anything."""
    leaf = reader.schema.leaf_by_path(tuple(column.split(".")))
    if leaf is None:
        raise KeyError(f"no such column {column!r}")
    dt = _FIXED_DTYPES.get(leaf.physical_type)
    if dt is None:
        raise TypeError(
            f"global span decode needs a fixed-width column; {column!r} is "
            f"{leaf.physical_type!r}"
        )
    return dt


def decode_row_span(reader, column: str, row_start: int, row_end: int,
                    prefetch: Optional[int] = None) -> np.ndarray:
    """Decode exactly rows [row_start, row_end) of a flat column on host.

    Touches only the row groups the span intersects (others are never read —
    the skipChunk discipline of chunk_reader.go:271-297 at row-group
    granularity) and slices boundary groups.  Column selection is narrowed to
    the one requested column for the duration of the call, so sibling chunks
    in touched row groups are seeked past, not decoded.

    ``prefetch`` > 0 routes each touched group through the reader's chunk
    pipeline (reader.FileReader prefetch semantics) — the per-SHARD decode
    pipeline: every shard of a work list overlaps its own IO and
    decompression independently, so a multi-host scan pipelines on every
    host without coordination.
    """
    dtype = column_span_dtype(reader, column)
    parts = []
    base = 0
    # touched groups + their row slices, planned up front so the pipelined
    # path can run ONE window across all of them (a per-group
    # read_row_group call would drain the pool at every boundary)
    touched = []  # (index, lo, hi, n)
    for i, rg in enumerate(reader.metadata.row_groups):
        n = rg.num_rows
        lo, hi = max(row_start - base, 0), min(row_end - base, n)
        if lo < hi:
            touched.append((i, lo, hi, n))
        base += n
        if base >= row_end:
            break
    k = _reader_prefetch(reader) if prefetch is None else int(prefetch)
    prev_selected = [tuple(l.path) for l in reader.schema.selected_leaves()]
    reader.schema.set_selected([tuple(column.split("."))])
    try:
        spans = {i: (lo, hi, n) for i, lo, hi, n in touched}
        if k > 0 and hasattr(reader, "_decode_row_groups"):
            groups = reader._decode_row_groups(sorted(spans), k)
        elif hasattr(reader, "_decode_row_groups"):
            # our FileReader: honor an explicit prefetch=0 even when the
            # reader's own setting is pipelined
            groups = ((i, reader.read_row_group(i, prefetch=0))
                      for i in sorted(spans))
        else:
            # generic reader contract: bare call only
            groups = ((i, reader.read_row_group(i)) for i in sorted(spans))
        for i, cols in groups:
            lo, hi, n = spans[i]
            cd = cols[column]
            vals = cd.values
            if len(vals) != n:
                raise ValueError(
                    f"decode_row_span requires a flat required column; "
                    f"{column!r} has {len(vals)} values for {n} rows"
                )
            parts.append(np.asarray(vals)[lo:hi])
    finally:
        reader.schema.set_selected(prev_selected)
    if not parts:
        return np.zeros(0, dtype=dtype)
    return np.concatenate(parts) if len(parts) > 1 else parts[0]


def _pad_span(local: np.ndarray, per: int, dtype: np.dtype) -> np.ndarray:
    """Zero-pad a decoded span to the uniform shard size (tail/empty shards)."""
    if len(local) >= per:
        return local
    return np.concatenate(
        [local.astype(dtype), np.zeros(per - len(local), dtype=dtype)]
    )


@scoped_x64
def global_column_array(
    reader, column: str, mesh: Mesh, axis: str = "data",
    prefetch: Optional[int] = None,
) -> tuple[jax.Array, int]:
    """Work-list → one global row-sharded device array (single-host form).

    Every addressable device in ``mesh`` stands in for one shard of the work
    list: shard i decodes its row span on host and its slice is placed on its
    device; ``jax.make_array_from_single_device_arrays`` stitches the global
    view without any cross-device exchange (row groups are assigned, not
    traded — SURVEY.md §5.7/5.8).  Returns (global_array, valid_rows):
    the tail shard is zero-padded to the uniform span size, so the global
    length is per*n — consumers mask with valid_rows.
    """
    total = int(reader.metadata.num_rows)
    # shard the work list along the NAMED axis only; other mesh axes (e.g. a
    # model axis on a 2-D mesh) see the same rows replicated — each span is
    # decoded once and placed on every device whose ``axis`` coordinate
    # matches, so the function serves any mesh rank, not just 1-D
    n = int(mesh.shape[axis])
    ax = mesh.axis_names.index(axis)
    spans = shard_row_ranges(total, n)
    per = spans[0][1] - spans[0][0] if total else 0
    sharding = NamedSharding(mesh, P(axis))
    dtype = column_span_dtype(reader, column)
    if not per:
        return jnp.zeros((0,), dtype=dtype), 0
    decoded = [
        _pad_span(decode_row_span(reader, column, lo, hi, prefetch=prefetch),
                  per, dtype)
        for lo, hi in spans
    ]
    pieces = [
        jax.device_put(decoded[idx[ax]], dev)
        for idx, dev in np.ndenumerate(mesh.devices)
    ]
    global_shape = (per * n,)
    arr = jax.make_array_from_single_device_arrays(global_shape, sharding, pieces)
    return arr, total


@scoped_x64
def process_local_column(
    reader, column: str, mesh: Mesh, axis: str = "data",
    prefetch: Optional[int] = None,
) -> tuple[jax.Array, int]:
    """True multi-host form: this process decodes only ITS span of the work
    list and contributes it via ``jax.make_array_from_process_local_data``.

    Each host computes the identical plan from the shared footer
    (shard_row_ranges over jax.process_count()), decodes the rows owned by
    its process, and the runtime assembles the global sharded array — the
    decode path's only cross-host traffic is the ICI/DCN assembly the
    consumer's pjit triggers.  On a single-process mesh this degrades to
    decoding everything locally, so the same code serves tests and clusters.
    """
    total = int(reader.metadata.num_rows)
    nproc = jax.process_count()
    spans = shard_row_ranges(total, nproc)
    lo, hi = spans[jax.process_index()]
    per = spans[0][1] - spans[0][0] if total else 0
    local = _pad_span(decode_row_span(reader, column, lo, hi,
                                      prefetch=prefetch), per,
                      column_span_dtype(reader, column))
    sharding = NamedSharding(mesh, P(axis))
    arr = jax.make_array_from_process_local_data(
        sharding, local, (per * nproc,)
    )
    return arr, total
