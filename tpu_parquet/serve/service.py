"""ScanService: many concurrent scan requests over one shared plan cache.

The north star is heavy traffic from many users, and the one-shot readers
are the wrong shape for it: every request re-parses, re-plans, and fights
every other request for memory with no arbitration.  This service puts a
bounded admission pipeline in front of the same readers:

    submit() ──bounded queue──► worker pool ──InFlightBudget──► reader
       │                           │
       └─ queue full: OverloadError (fast-reject, never a blocked caller)
                                   └─ per-request p50/p95 latency SLOs

- **Shared state**: one :class:`~tpu_parquet.serve.PlanCache` — footers,
  ScanPlan IR (route + pruning memos), and decoded dictionaries read
  through it, so concurrent requests over a working set parse each file's
  metadata once (cache counters prove it in tests).
- **Admission control**: a bounded request queue (``TPQ_SERVE_QUEUE``) +
  ``TPQ_SERVE_CONCURRENCY`` workers; each admitted request charges its
  plan's :meth:`~tpu_parquet.scanplan.ScanPlan.estimated_bytes` against one
  shared :class:`~tpu_parquet.alloc.InFlightBudget` (``max_memory``) before
  reading a byte — backpressure between requests, OverloadError at the
  door.
- **SLOs**: per-request queue-wait and execution latencies land in
  :class:`~tpu_parquet.obs.LatencyHistogram`\\ s under the registry
  ``serve`` section (``pq_tool serve-stats`` prints the table;
  ``pq_tool doctor`` says ``admission-bound`` when queue-wait dominates).
- **Hang containment**: with ``hang_s`` (or ``TPQ_HANG_S``) each executing
  request is watched by its own :class:`~tpu_parquet.obs.Watchdog`; a
  stalled store fetch dumps flight state (the dump's ``serve`` sample
  names the stuck request) and aborts THAT request with
  :class:`~tpu_parquet.errors.HangError` — the other clients never notice.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time

from ..alloc import InFlightBudget
from ..errors import OverloadError
from ..obs import (LatencyHistogram, env_int, register_flight_source,
                   resolve_hang_s)
from .cache import BoundDictCache, PlanCache

__all__ = ["ScanRequest", "ScanService", "ScanTicket", "ServeStats"]

_req_ids = itertools.count(1)


def _count_rows(result: dict) -> int:
    """Best-effort served-row accounting over a response tree ({path:
    {column: ColumnData | DeviceColumnData | [per-row-group parts]}}).
    Accounting only — it must never be able to fail a completed request."""
    rows = 0
    for cols in result.values():
        if not cols:
            continue
        first = next(iter(cols.values()))
        parts = first if isinstance(first, list) else [first]
        rows += sum(int(getattr(p, "num_leaf_slots", 0) or 0)
                    for p in parts)
    return rows


class ScanRequest:
    """One scan: a file set + projection + predicate + response shape.

    ``paths``: the files (str/PathLike), scanned in order.  ``columns``:
    projection (None = all).  ``filter``: a :mod:`~tpu_parquet.predicate`
    Predicate or its text form (``parse_filter`` grammar); yielded rows are
    the readers' usual superset contract.  ``prefetch``: per-file chunk
    pipeline depth.  ``device=True`` decodes to device arrays through
    ``DeviceFileReader`` (host ``FileReader`` otherwise — the fixed shape
    of a batched response is the loader's job; this service returns the
    reader's columnar output per file).
    """

    __slots__ = ("paths", "columns", "filter", "prefetch", "device",
                 "validate_crc")

    def __init__(self, paths, columns=None, filter=None,  # noqa: A002
                 prefetch: int = 0, device: bool = False,
                 validate_crc=None):
        import os

        self.paths = ([paths] if isinstance(paths, (str, bytes, os.PathLike))
                      else list(paths))
        self.columns = columns
        self.filter = filter
        self.prefetch = int(prefetch)
        self.device = bool(device)
        self.validate_crc = validate_crc


class ScanTicket:
    """The admission receipt: ``result(timeout)`` blocks for the response
    (re-raising the request's failure), ``done()`` polls."""

    __slots__ = ("id", "_event", "_result", "_exc", "queue_wait_s",
                 "exec_s")

    def __init__(self, rid: int):
        self.id = rid
        self._event = threading.Event()
        self._result = None
        self._exc: "BaseException | None" = None
        self.queue_wait_s = 0.0
        self.exec_s = 0.0

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: "float | None" = None):
        if not self._event.wait(timeout):
            raise TimeoutError(f"scan request #{self.id} still running")
        if self._exc is not None:
            raise self._exc
        return self._result

    def _finish(self, result=None, exc: "BaseException | None" = None):
        self._result = result
        self._exc = exc
        self._event.set()


class ServeStats:
    """Service counters (all flows except the gauges; composes by addition
    in the registry ``serve`` section)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.submitted = 0
        self.completed = 0
        self.rejected = 0
        self.failed = 0
        self.queue_wait_seconds = 0.0
        self.exec_seconds = 0.0
        self.rows = 0
        self.queue_depth_peak = 0

    def as_dict(self) -> dict:
        with self.lock:
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "rejected": self.rejected,
                "failed": self.failed,
                "queue_wait_seconds": round(self.queue_wait_seconds, 6),
                "exec_seconds": round(self.exec_seconds, 6),
                "rows": self.rows,
                "queue_depth_peak": self.queue_depth_peak,
            }


class ScanService:
    """The concurrent scan front end.  Construct once, ``submit()`` from
    any thread, ``close()`` when done (context manager supported)."""

    def __init__(self, concurrency: "int | None" = None,
                 queue_depth: "int | None" = None, max_memory: int = 0,
                 cache: "PlanCache | None" = None, store=None,
                 hang_s=None, validate_crc=None):
        if concurrency is None:
            concurrency = env_int("TPQ_SERVE_CONCURRENCY", 4, lo=1)
        if queue_depth is None:
            queue_depth = env_int("TPQ_SERVE_QUEUE", 2 * concurrency, lo=1)
        self.concurrency = int(concurrency)
        self.cache = cache if cache is not None else PlanCache()
        self.stats = ServeStats()
        self._store = store  # per-file ByteStore factory (iostore contract)
        self._hang_s = hang_s
        self._validate_crc = validate_crc
        # admission: bounded queue (fast-reject) + shared memory budget
        # (backpressure between ADMITTED requests, charged from the plan
        # IR's byte estimate before any byte is read)
        self._q: "queue.Queue" = queue.Queue(maxsize=int(queue_depth))
        self._budget = InFlightBudget(int(max_memory))
        self._hist_wait = LatencyHistogram()
        self._hist_exec = LatencyHistogram()
        self._hist_total = LatencyHistogram()
        self._inflight: dict = {}  # rid -> (path0, t_start)
        self._inflight_lock = threading.Lock()
        self._closed = False
        # serializes the closed-check+enqueue in submit() against close()'s
        # drain+sentinels: without it a racing submit can land its item
        # BEHIND the shutdown sentinels — never processed, never finished,
        # a caller blocked in result() forever
        self._submit_lock = threading.Lock()
        self._workers = [
            threading.Thread(target=self._worker, name=f"tpq-serve-{i}",
                             daemon=True)
            for i in range(self.concurrency)
        ]
        for t in self._workers:
            t.start()
        # a wedged process's flight dump must name the stuck request —
        # autopsy prints this sample's oldest in-flight entry
        register_flight_source("serve", self, "sample")

    # -- submission ------------------------------------------------------------

    def submit(self, request: ScanRequest) -> ScanTicket:
        """Admit one request; raises :class:`OverloadError` IMMEDIATELY
        when the queue is full (load shedding, never a blocked caller)."""
        ticket = ScanTicket(next(_req_ids))
        try:
            with self._submit_lock:
                if self._closed:
                    raise RuntimeError("ScanService is closed")
                self._q.put_nowait((ticket, request, time.perf_counter()))
        except queue.Full:
            with self.stats.lock:
                self.stats.rejected += 1
                inflight = len(self._inflight)
            raise OverloadError(
                f"scan service overloaded: queue full "
                f"({self._q.maxsize} queued, {inflight} in flight)",
                queue_depth=self._q.maxsize, in_flight=inflight) from None
        with self.stats.lock:
            self.stats.submitted += 1
            self.stats.queue_depth_peak = max(self.stats.queue_depth_peak,
                                              self._q.qsize())
        return ticket

    def scan(self, request: ScanRequest, timeout: "float | None" = None):
        """Submit + wait: the one-call form."""
        return self.submit(request).result(timeout)

    # -- workers ---------------------------------------------------------------

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            ticket, request, t_submit = item
            t_start = time.perf_counter()
            wait = t_start - t_submit
            ticket.queue_wait_s = wait
            self._hist_wait.record(wait)
            first = request.paths[0] if request.paths else None
            with self._inflight_lock:
                self._inflight[ticket.id] = (str(first), t_start)
            try:
                result, exc = self._execute(request), None
            except BaseException as e:  # noqa: BLE001 — delivered to caller
                result, exc = None, e
            # ALL bookkeeping lands before _finish sets the ticket's event:
            # a caller waking from result() must read final exec_s/stats,
            # never a zero the worker hadn't written yet
            t_end = time.perf_counter()
            ticket.exec_s = t_end - t_start
            self._hist_exec.record(ticket.exec_s)
            self._hist_total.record(t_end - t_submit)
            with self._inflight_lock:
                self._inflight.pop(ticket.id, None)
            with self.stats.lock:
                self.stats.queue_wait_seconds += wait
                self.stats.exec_seconds += ticket.exec_s
                if exc is not None:
                    self.stats.failed += 1
                else:
                    self.stats.completed += 1
                    self.stats.rows += _count_rows(result)
            if exc is not None:
                ticket._finish(exc=exc)
            else:
                ticket._finish(result=result)

    def _resolve_filter(self, request: ScanRequest):
        flt = request.filter
        if isinstance(flt, str):
            from ..predicate import parse_filter

            return parse_filter(flt)
        return flt

    def _execute(self, request: ScanRequest) -> dict:
        """Run one request over the shared cache: per file, read the
        footer/plan through it, charge the plan's byte estimate against
        the admission budget, then scan with a plan-replaying reader.
        Returns ``{path: {column: ColumnData}}`` in request order."""
        from ..reader import FileReader

        pred = self._resolve_filter(request)
        out: dict = {}
        for path in request.paths:
            key = self.cache.file_key(path)
            meta, schema = self.cache.footer(path)
            plan = self.cache.plan(key, request.columns, pred,
                                   meta=meta, schema=schema)
            charge = min(plan.estimated_bytes(),
                         max(self._budget.max_bytes, 0)) \
                if self._budget.max_bytes > 0 else 0
            if charge:
                self._budget.acquire(charge)
            try:
                kw = dict(columns=request.columns, metadata=meta,
                          row_filter=pred, prefetch=request.prefetch,
                          validate_crc=(request.validate_crc
                                        if request.validate_crc is not None
                                        else self._validate_crc),
                          store=self._store, plan=plan,
                          dict_cache=BoundDictCache(self.cache, key))
                if request.device:
                    from ..device_reader import DeviceFileReader

                    with DeviceFileReader(path, hang_s=self._hang_s,
                                          **kw) as r:
                        cols: dict = {}
                        for group in r.iter_row_groups():
                            for name, cd in group.items():
                                cols.setdefault(name, []).append(cd)
                        out[str(path)] = {
                            name: parts[0] if len(parts) == 1 else parts
                            for name, parts in cols.items()}
                else:
                    with FileReader(path, **kw) as r:
                        out[str(path)] = self._read_watched(r)
            finally:
                if charge:
                    self._budget.release(charge)
        return out

    def _read_watched(self, r) -> dict:
        """``read_all`` under a per-request watchdog: a stalled store fetch
        (the transport wedge) dumps flight state and aborts THIS request
        with HangError while every other worker keeps serving.  Mirrors
        DeviceFileReader's own watchdog wiring — the host FileReader has
        none of its own."""
        from ..obs import Watchdog

        wd = Watchdog(resolve_hang_s(self._hang_s))
        if not wd.enabled or r._store.stats is None:
            # a plain local store cannot stall (os.pread either returns or
            # errors), and its counters don't tick on the sequential path —
            # arming the dog there would misread a long clean read as a
            # wedge.  Stall containment is for instrumented range stores.
            return r.read_all()
        wd.watch("pipeline", lambda: r._pipe_stats.sample())
        wd.watch("iostore", r._store.stats.progress)
        wd.add_abort_hook(r._store.abort)
        wd.start()
        try:
            out = r.read_all()
            wd.check()  # surface a fired raise-policy HangError
            return out
        finally:
            wd.stop()

    # -- lifecycle -------------------------------------------------------------

    def close(self) -> None:
        """Drain-free shutdown: queued-but-unstarted requests fail with
        OverloadError; executing requests finish."""
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
        drained = []
        try:
            while True:
                drained.append(self._q.get_nowait())
        except queue.Empty:
            pass
        for item in drained:
            if item is not None:
                with self.stats.lock:
                    # accounted as rejections so the serve section always
                    # reconciles: submitted == completed + failed + rejected
                    self.stats.rejected += 1
                item[0]._finish(exc=OverloadError(
                    "scan service closed before this request started"))
        for _ in self._workers:
            self._q.put(None)
        for t in self._workers:
            t.join(timeout=60)

    def __enter__(self) -> "ScanService":
        return self

    def __exit__(self, *exc):
        self.close()

    # -- reporting -------------------------------------------------------------

    def sample(self) -> dict:
        """Live admission state (flight dumps + obs.Sampler track): queue
        depth, in-flight requests with ages, and the cache counters."""
        now = time.perf_counter()
        with self._inflight_lock:
            inflight = {str(rid): {"path": p, "age_s": round(now - t0, 6)}
                        for rid, (p, t0) in self._inflight.items()}
        oldest = max((v["age_s"] for v in inflight.values()), default=0.0)
        return {
            "queue_depth": self._q.qsize(),
            "in_flight": len(inflight),
            "oldest_request_s": oldest,
            "requests": inflight,
            "cache": self.cache.counters(),
        }

    def serve_stats(self) -> dict:
        """The registry ``serve`` section: counters + cache counters."""
        return {**self.stats.as_dict(), "cache": self.cache.counters()}

    def obs_registry(self):
        """Unified metrics tree: the ``serve`` section plus the request
        latency histograms (``serve.queue_wait`` / ``serve.exec`` /
        ``serve.request`` — the p50/p95 SLO surface)."""
        from ..obs import StatsRegistry

        reg = StatsRegistry()
        reg.add_serve(self.serve_stats())
        reg.histogram("serve.queue_wait").merge_from(self._hist_wait)
        reg.histogram("serve.exec").merge_from(self._hist_exec)
        reg.histogram("serve.request").merge_from(self._hist_total)
        return reg
