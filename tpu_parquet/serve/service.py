"""ScanService: many concurrent scan requests over one shared plan cache.

The north star is heavy traffic from many users, and the one-shot readers
are the wrong shape for it: every request re-parses, re-plans, and fights
every other request for memory with no arbitration.  This service puts a
bounded admission pipeline in front of the same readers:

    submit() ──bounded queue──► worker pool ──InFlightBudget──► reader
       │                           │
       └─ queue full: OverloadError (fast-reject, never a blocked caller)
                                   └─ per-request p50/p95 latency SLOs

- **Shared state**: one :class:`~tpu_parquet.serve.PlanCache` — footers,
  ScanPlan IR (route + pruning memos), and decoded dictionaries read
  through it, so concurrent requests over a working set parse each file's
  metadata once (cache counters prove it in tests).
- **Admission control**: a bounded request queue (``TPQ_SERVE_QUEUE``) +
  ``TPQ_SERVE_CONCURRENCY`` workers; each admitted request charges its
  plan's :meth:`~tpu_parquet.scanplan.ScanPlan.estimated_bytes` against one
  shared :class:`~tpu_parquet.alloc.InFlightBudget` (``max_memory``) before
  reading a byte — backpressure between requests, OverloadError at the
  door.
- **SLOs**: per-request queue-wait and execution latencies land in
  :class:`~tpu_parquet.obs.LatencyHistogram`\\ s under the registry
  ``serve`` section (``pq_tool serve-stats`` prints the table;
  ``pq_tool doctor`` says ``admission-bound`` when queue-wait dominates).
- **Hang containment**: with ``hang_s`` (or ``TPQ_HANG_S``) each executing
  request is watched by its own :class:`~tpu_parquet.obs.Watchdog`; a
  stalled store fetch dumps flight state (the dump's ``serve`` sample
  names the stuck request) and aborts THAT request with
  :class:`~tpu_parquet.errors.HangError` — the other clients never notice.
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
import weakref
from collections.abc import Mapping
from contextlib import nullcontext

from ..alloc import InFlightBudget
from ..errors import (CancelledError, DeadlineExceededError, HangError,
                      OverloadError, ParquetError, RetryExhaustedError,
                      TransientIOError)
from ..obs import (LatencyHistogram, MetricsDumper, RequestTrace,
                   TailSampler, env_float, env_int, register_flight_source,
                   resolve_hang_s, set_request_trace)
from ..resilience import BreakerBoard, CancelToken
from .cache import BoundDictCache, PlanCache
from .stream import (StreamingScan, check_cursor_compatible, request_digest,
                     unpack_cursor)
from .tenancy import (DEFAULT_TENANT, FairScheduler, TenantRegistry,
                      fair_enabled)

__all__ = ["ScanRequest", "ScanService", "ScanTicket", "ServeStats"]

# request priority bands (ScanRequest.priority): brownout sheds from the
# bottom up — LOW goes first, NORMAL under deeper pressure, HIGH only when
# the queue is physically full
PRIORITY_LOW, PRIORITY_NORMAL, PRIORITY_HIGH = 0, 1, 2

# the failure classes a circuit breaker counts: transport exhaustion,
# transient-surfaced faults, malformed data, and transport wedges — all
# properties of the FILE/STORE, not of the caller (deadline expiry and
# caller cancellation are deliberately absent: an impatient client must
# never open a healthy file's circuit)
_CLASSIFIED_FAILURES = (RetryExhaustedError, TransientIOError, ParquetError,
                        HangError)

_req_ids = itertools.count(1)


def _span(trace, name, **args):
    """A RequestTrace span, or a no-op when the request carries no trace
    (tracing off) — keeps the instrumented call sites branch-free."""
    return trace.span(name, **args) if trace is not None else nullcontext()


def _count_rows(result: dict) -> int:
    """Best-effort served-row accounting over a response tree ({path:
    {column: ColumnData | DeviceColumnData | [per-row-group parts]}}).
    Accounting only — it must never be able to fail a completed request."""
    rows = 0
    for cols in result.values():
        if not cols:
            continue
        first = next(iter(cols.values()))
        parts = first if isinstance(first, list) else [first]
        rows += sum(int(getattr(p, "num_leaf_slots", 0) or 0)
                    for p in parts)
    return rows


class ScanRequest:
    """One scan: a file set + projection + predicate + response shape.

    ``paths``: the files (str/PathLike), scanned in order.  ``columns``:
    projection (None = all).  ``filter``: a :mod:`~tpu_parquet.predicate`
    Predicate or its text form (``parse_filter`` grammar); yielded rows are
    the readers' usual superset contract.  ``prefetch``: per-file chunk
    pipeline depth.  ``device=True`` decodes to device arrays through
    ``DeviceFileReader`` (host ``FileReader`` otherwise — the fixed shape
    of a batched response is the loader's job; this service returns the
    reader's columnar output per file).

    ``deadline_s`` is the request's END-TO-END budget (queue wait
    included): when it expires the request stops issuing new IO at the
    next unit boundary, frees its admission-budget charge, and raises
    :class:`~tpu_parquet.errors.DeadlineExceededError` for this caller
    only.  ``priority`` (:data:`PRIORITY_LOW` / ``NORMAL`` / ``HIGH``)
    feeds brownout shedding: under ``TPQ_SERVE_BROWNOUT`` pressure the
    low band is shed first with a drain-rate ``retry_after_s`` hint while
    high-priority traffic still admits.

    ``tenant`` names the requester for fair-share admission, budget
    slicing, and per-tenant SLO accounting (unset = the default tenant).
    ``stream=True`` returns a :class:`~tpu_parquet.serve.StreamingScan`
    session from ``scan()``/``result()`` instead of a materialized
    response: iterate it for fixed-shape ``batch_rows``-row padded+masked
    batches.  ``cursor`` resumes a streaming session from a prior
    session's :meth:`~tpu_parquet.serve.StreamingScan.cursor` blob
    (validated at submit time; a mismatched request shape raises
    :class:`~tpu_parquet.errors.CheckpointError`).
    """

    __slots__ = ("paths", "columns", "filter", "prefetch", "device",
                 "validate_crc", "deadline_s", "priority", "tenant",
                 "stream", "batch_rows", "cursor")

    def __init__(self, paths, columns=None, filter=None,  # noqa: A002
                 prefetch: int = 0, device: bool = False,
                 validate_crc=None, deadline_s: "float | None" = None,
                 priority: int = PRIORITY_NORMAL,
                 tenant: "str | None" = None, stream: bool = False,
                 batch_rows: int = 1024,
                 cursor: "bytes | None" = None):
        import os

        self.paths = ([paths] if isinstance(paths, (str, bytes, os.PathLike))
                      else list(paths))
        self.columns = columns
        self.filter = filter
        self.prefetch = int(prefetch)
        self.device = bool(device)
        self.validate_crc = validate_crc
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.priority = min(max(int(priority), PRIORITY_LOW), PRIORITY_HIGH)
        self.tenant = DEFAULT_TENANT if not tenant else str(tenant)
        self.stream = bool(stream)
        self.batch_rows = int(batch_rows)
        self.cursor = cursor


class ScanTicket:
    """The admission receipt: ``result(timeout)`` blocks for the response
    (re-raising the request's failure), ``done()`` polls, ``cancel()``
    takes the request back — it stops issuing new IO at the next unit
    boundary, releases what it held, and ``result()`` raises
    :class:`~tpu_parquet.errors.CancelledError`."""

    __slots__ = ("id", "token", "_event", "_result", "_exc", "queue_wait_s",
                 "exec_s")

    def __init__(self, rid: int, token: "CancelToken | None" = None):
        self.id = rid
        self.token = token if token is not None else CancelToken()
        self._event = threading.Event()
        self._result = None
        self._exc: "BaseException | None" = None
        self.queue_wait_s = 0.0
        self.exec_s = 0.0

    def done(self) -> bool:
        return self._event.is_set()

    def cancel(self) -> None:
        """Cancel this request (idempotent; the first cause wins).  A
        queued request fails the moment a worker picks it up; an executing
        one stops at its next unit boundary — either way its budget bytes
        release and no new IO is issued."""
        self.token.cancel(CancelledError(
            f"scan request #{self.id} cancelled by caller"))

    def result(self, timeout: "float | None" = None):
        if not self._event.wait(timeout):
            raise TimeoutError(f"scan request #{self.id} still running")
        if self._exc is not None:
            raise self._exc
        return self._result

    def _finish(self, result=None, exc: "BaseException | None" = None):
        self._result = result
        self._exc = exc
        self._event.set()


class ServeStats:
    """Service counters (all flows except the gauges; composes by addition
    in the registry ``serve`` section)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.submitted = 0
        self.completed = 0
        self.rejected = 0
        self.failed = 0
        self.queue_wait_seconds = 0.0
        self.exec_seconds = 0.0
        self.rows = 0
        self.queue_depth_peak = 0
        # request-lifecycle outcomes (subsets of `failed`).  Accounting
        # contract: `submitted` counts ADMITTED requests only; `rejected`
        # counts never-admitted ones (queue-full + brownout sheds, which
        # never enter `submitted`) plus close()-drained tickets (which
        # do) — so admitted work reconciles as submitted == completed +
        # failed + drained, while sheds/fast-rejects stand apart as the
        # load the service refused at the door.
        self.deadline_exceeded = 0
        self.cancelled = 0
        # brownout sheds by priority band (subsets of `rejected`)
        self.shed_low = 0
        self.shed_normal = 0
        # streaming sessions admitted (subset of `submitted`) + batches
        # delivered; retry_after_hint_s is a GAUGE — the back-off hint the
        # most recent shed/reject carried (obs merges max it)
        self.stream_sessions = 0
        self.stream_batches = 0
        # mid-stream worker-slot yields (stream-aware fair scheduling):
        # how many times a session parked so another tenant could run
        self.stream_yields = 0
        self.retry_after_hint_s = 0.0

    def as_dict(self) -> dict:
        with self.lock:
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "rejected": self.rejected,
                "failed": self.failed,
                "queue_wait_seconds": round(self.queue_wait_seconds, 6),
                "exec_seconds": round(self.exec_seconds, 6),
                "rows": self.rows,
                "queue_depth_peak": self.queue_depth_peak,
                "deadline_exceeded": self.deadline_exceeded,
                "cancelled": self.cancelled,
                "sheds": {"low": self.shed_low, "normal": self.shed_normal},
                "stream_sessions": self.stream_sessions,
                "stream_batches": self.stream_batches,
                "stream_yields": self.stream_yields,
                "retry_after_hint_s": self.retry_after_hint_s,
            }


class ScanService:
    """The concurrent scan front end.  Construct once, ``submit()`` from
    any thread, ``close()`` when done (context manager supported)."""

    def __init__(self, concurrency: "int | None" = None,
                 queue_depth: "int | None" = None, max_memory: int = 0,
                 cache: "PlanCache | None" = None, store=None,
                 hang_s=None, validate_crc=None,
                 brownout: "float | None" = None,
                 breakers: "BreakerBoard | None" = None,
                 result_cache_mb: "int | None" = None,
                 result_cache_hbm_mb: "int | None" = None,
                 tenants: "TenantRegistry | Mapping | str | None" = None,
                 fair: "bool | None" = None,
                 stream_yield: "bool | None" = None):
        from ..iostore import ByteStore

        if concurrency is None:
            concurrency = env_int("TPQ_SERVE_CONCURRENCY", 4, lo=1)
        if queue_depth is None:
            queue_depth = env_int("TPQ_SERVE_QUEUE", 2 * concurrency, lo=1)
        self.concurrency = int(concurrency)
        # result_cache_mb/_hbm_mb size the decoded-result tier explicitly
        # (the TPQ_RESULT_CACHE_* knobs otherwise decide): with it on, a
        # hot repeated scan becomes a pure cache lookup + batch assembly
        # (see serve/result_cache.py)
        self.cache = (cache if cache is not None
                      else PlanCache(result_cache_mb=result_cache_mb,
                                     result_cache_hbm_mb=result_cache_hbm_mb))
        self.stats = ServeStats()
        self._hang_s = hang_s
        self._validate_crc = validate_crc
        # brownout load shedding: when queue occupancy or held budget
        # bytes cross this fraction, low-priority requests shed with a
        # drain-rate retry_after_s; halfway from there to full, normal
        # priority sheds too — high admits until the queue is physically
        # full.  0 disables.
        self.brownout = (env_float("TPQ_SERVE_BROWNOUT", 0.85, lo=0.0,
                                   hi=1.0)
                         if brownout is None else float(brownout))
        # per-file circuit breakers keyed by the PlanCache generation key
        # (a rewritten file starts with a clean breaker)
        self.breakers = breakers if breakers is not None else BreakerBoard()
        # per-file ByteStore factory (iostore contract), wrapped so the
        # service can fold every created store's IOStats (retries, hedges)
        # into its own registry tree.  Live stores are WEAKLY held (they
        # stay collectable), and each factory store's counters are folded
        # into a service-owned aggregate when its reader CLOSES it —
        # without the fold, a completed request's stats would be
        # garbage-collected with its store and the io section would
        # report zeros for all finished work.
        self._served_stores: "weakref.WeakSet" = weakref.WeakSet()
        self._io_agg: dict = {}
        self._io_agg_lock = threading.Lock()
        if store is None:
            self._store = None
        elif isinstance(store, ByteStore):
            self._store = store
            if store.stats is not None:
                self._served_stores.add(store)
        elif callable(store):
            def _capturing_factory(f, _orig=store):
                st = _orig(f)
                if getattr(st, "stats", None) is not None:
                    self._served_stores.add(st)
                    orig_close = st.close

                    def _close_and_fold(_st=st, _close=orig_close):
                        _close()
                        self._fold_io(_st)

                    st.close = _close_and_fold
                return st

            self._store = _capturing_factory
        else:
            self._store = store  # resolve_store raises its typed error
        # admission: bounded multi-tenant scheduler (fast-reject; deficit
        # round-robin across per-tenant queues unless TPQ_SERVE_FAIR=0
        # degrades it to global FIFO) + shared memory budget (backpressure
        # between ADMITTED requests, charged from the plan IR's byte
        # estimate before any byte is read).  Each tenant also carries its
        # own weight-proportional budget SLICE (tenancy.py) charged before
        # the global budget — one tenant's giant scans queue behind that
        # tenant's slice, not the fleet's.
        self._q = FairScheduler(int(queue_depth), fair=fair_enabled(fair))
        self._budget = InFlightBudget(int(max_memory))
        if tenants is None:
            tenants = TenantRegistry(max_memory=int(max_memory))
        elif isinstance(tenants, str):
            tenants = TenantRegistry(max_memory=int(max_memory), spec=tenants)
        elif isinstance(tenants, Mapping):
            reg = TenantRegistry(max_memory=int(max_memory), spec="")
            for name, weight in tenants.items():
                reg.register(str(name), weight=int(weight))
            tenants = reg
        elif not isinstance(tenants, TenantRegistry):
            raise TypeError(
                "tenants= must be a TenantRegistry, a {name: weight} "
                f"mapping, or a spec string, not {type(tenants).__name__}")
        self.tenants = tenants
        if tenants is not None and int(max_memory) > 0:
            self.tenants.set_max_memory(int(max_memory))
        # live streaming sessions by ticket id — close() aborts them so a
        # blocked next() caller gets its terminal verdict, not a hang
        self._streams: dict = {}
        self._hist_wait = LatencyHistogram()
        self._hist_exec = LatencyHistogram()
        self._hist_total = LatencyHistogram()
        # request tracing: every admitted request carries a RequestTrace on
        # its cancel token; the tail sampler keeps the interesting trees
        # (slow / errored / deadline / shed / 1-in-N) in a byte-bounded
        # ring.  Per-instance (env-tuned at construction) so one test's or
        # service's retention never bleeds into another's.
        self.sampler = TailSampler()
        # periodic registry snapshots (TPQ_METRICS_DUMP=path:interval_s) —
        # the file `pq_tool metrics --watch` polls; inert when unset
        self._dumper = MetricsDumper(self.obs_registry)
        self._dumper.start()
        # fleet spool: per-process snapshots into TPQ_OBS_SPOOL (obs_fleet;
        # inert when unset) — what FleetAggregator / `pq_tool top` read
        from ..obs_fleet import SpoolWriter

        self._spool = SpoolWriter(self.obs_registry, role="serve",
                                  sampler=self.sampler)
        self._spool.start()
        # stream-aware fair scheduling: a streaming session hands its
        # worker slot back between batches while another tenant has queued
        # work (DRR at batch granularity).  Only meaningful under fair
        # scheduling; TPQ_SERVE_STREAM_YIELD=0 (or stream_yield=False)
        # pins a session to its slot for its whole lifetime (the old
        # behavior, and the bench A/B).
        if stream_yield is None:
            stream_yield = os.environ.get(
                "TPQ_SERVE_STREAM_YIELD", "1") != "0"
        self._stream_yield = bool(stream_yield) and self._q.fair
        self._inflight: dict = {}  # rid -> (path0, t_start)
        self._inflight_lock = threading.Lock()
        self._closed = False
        # serializes the closed-check+enqueue in submit() against close()'s
        # drain+sentinels: without it a racing submit can land its item
        # BEHIND the shutdown sentinels — never processed, never finished,
        # a caller blocked in result() forever
        self._submit_lock = threading.Lock()
        self._workers = [
            threading.Thread(target=self._worker, name=f"tpq-serve-{i}",
                             daemon=True)
            for i in range(self.concurrency)
        ]
        for t in self._workers:
            t.start()
        # a wedged process's flight dump must name the stuck request —
        # autopsy prints this sample's oldest in-flight entry
        register_flight_source("serve", self, "sample")

    # -- submission ------------------------------------------------------------

    def _occupancy(self) -> float:
        """Admission pressure in [0, 1]: the FULLER of the request queue
        and the in-flight memory budget (either one saturating is the
        brownout signal — a deep queue of tiny requests and a shallow
        queue of huge ones both mean new work will wait)."""
        q_frac = self._q.qsize() / self._q.maxsize if self._q.maxsize else 0.0
        b = self._budget
        b_frac = (b.held / b.max_bytes) if b.max_bytes > 0 else 0.0
        return max(q_frac, min(b_frac, 1.0))

    def _retry_after_s(self) -> float:
        """Back-off hint from the observed drain rate: roughly how long
        until the current backlog clears one worker slot (floored so a
        cold service never tells a caller to retry in 0 seconds)."""
        with self.stats.lock:
            completed = self.stats.completed
            exec_s = self.stats.exec_seconds
        avg = (exec_s / completed) if completed else 0.05
        backlog = self._q.qsize() + len(self._inflight)
        return round(max(backlog * avg / max(self.concurrency, 1), 0.05), 3)

    def register_tenant(self, name: str, weight: int = 1,
                        slo_p99_ms: "float | None" = None,
                        cache_fraction: "float | None" = None,
                        deadline_s: "float | None" = None):
        """Configure a tenant's QoS: fair-share ``weight``, optional SLO
        target (the ``serve.tenants`` subtree and doctor read it), an
        optional fraction of the result cache its inserts may hold, and an
        optional default request deadline (inherited by requests that set
        no ``deadline_s`` of their own)."""
        t = self.tenants.register(name, weight=weight, slo_p99_ms=slo_p99_ms,
                                  cache_fraction=cache_fraction,
                                  deadline_s=deadline_s)
        self.cache.results.set_tenant_share(name, cache_fraction)
        return t

    def _maybe_shed(self, request: ScanRequest, tenant) -> None:
        """Brownout gate: shed low-priority work at ``brownout``
        occupancy and normal-priority work halfway from there to full —
        graceful degradation instead of a cliff, with the shed caller
        handed ``retry_after_s`` and the admission snapshot."""
        if self.brownout <= 0 or request.priority >= PRIORITY_HIGH:
            return
        occ = self._occupancy()
        threshold = self.brownout
        if request.priority >= PRIORITY_NORMAL:
            threshold = self.brownout + (1.0 - self.brownout) / 2
        if occ < threshold:
            return
        hint = self._retry_after_s()
        with self.stats.lock:
            self.stats.rejected += 1
            if request.priority <= PRIORITY_LOW:
                self.stats.shed_low += 1
            else:
                self.stats.shed_normal += 1
            self.stats.retry_after_hint_s = hint
            inflight = len(self._inflight)
        with tenant.lock:
            tenant.rejected += 1
            if request.priority <= PRIORITY_LOW:
                tenant.shed_low += 1
            else:
                tenant.shed_normal += 1
        band = "low" if request.priority <= PRIORITY_LOW else "normal"
        raise OverloadError(
            f"scan service browning out ({occ:.0%} occupancy >= "
            f"{threshold:.0%}): shedding {band}-priority work of tenant "
            f"{tenant.name!r}",
            queue_depth=self._q.qsize(), in_flight=inflight,
            retry_after_s=hint, shed_priority=request.priority)

    def submit(self, request: ScanRequest) -> ScanTicket:
        """Admit one request; raises :class:`OverloadError` IMMEDIATELY
        when the queue is full (load shedding, never a blocked caller) or
        when brownout sheds this priority band (``retry_after_s`` set).
        The returned ticket's ``cancel()`` and the request's
        ``deadline_s`` both flow into every downstream read.

        A ``stream=True`` request's ticket resolves to a
        :class:`~tpu_parquet.serve.StreamingScan` session the moment a
        worker picks it up; a resume ``cursor`` is validated HERE,
        synchronously, so a mismatched blob fails the caller typed and
        immediately rather than mid-stream."""
        tenant = self.tenants.get(request.tenant)
        # an explicit request deadline always wins; otherwise the tenant's
        # registered default applies (None -> no deadline, as before)
        deadline = (request.deadline_s if request.deadline_s is not None
                    else tenant.deadline_s)
        ticket = ScanTicket(next(_req_ids),
                            CancelToken.with_timeout(deadline))
        if self.sampler.enabled:
            # the trace rides the cancel token into every downstream layer
            # (readers, prefetch pipeline, iostore, device dispatch); the
            # zero-length "submit" span carries the request's identity
            trace = RequestTrace()
            t_sub = time.perf_counter()
            trace.add_timed("submit", t_sub, t_sub, request=ticket.id,
                            tenant=tenant.name, paths=len(request.paths),
                            stream=bool(request.stream),
                            device=bool(request.device),
                            priority=int(request.priority))
            ticket.token.trace = trace
        self._maybe_shed(request, tenant)
        session = None
        if request.stream:
            state = None
            if request.cursor is not None:
                state = unpack_cursor(request.cursor)
                check_cursor_compatible(state, {
                    "batch_rows": int(request.batch_rows),
                    "device": bool(request.device),
                    "n_paths": len(request.paths),
                    "request_digest": request_digest(request),
                })
            session = StreamingScan(self, request, ticket, tenant,
                                    resume_state=state)
            with self._inflight_lock:
                self._streams[ticket.id] = session
        try:
            with self._submit_lock:
                if self._closed:
                    raise RuntimeError("ScanService is closed")
                self._q.put_nowait(
                    tenant.name, tenant.weight,
                    (ticket, request, time.perf_counter(), session))
        except queue.Full:
            if session is not None:
                with self._inflight_lock:
                    self._streams.pop(ticket.id, None)
            hint = self._retry_after_s()
            with self.stats.lock:
                self.stats.rejected += 1
                self.stats.retry_after_hint_s = hint
                inflight = len(self._inflight)
            with tenant.lock:
                tenant.rejected += 1
            raise OverloadError(
                f"scan service overloaded: queue full "
                f"({self._q.maxsize} queued, {inflight} in flight; "
                f"tenant {tenant.name!r})",
                queue_depth=self._q.maxsize, in_flight=inflight,
                retry_after_s=hint) from None
        except BaseException:
            if session is not None:  # closed-service raise: no stale entry
                with self._inflight_lock:
                    self._streams.pop(ticket.id, None)
            raise
        with self.stats.lock:
            self.stats.submitted += 1
            if session is not None:
                self.stats.stream_sessions += 1
            self.stats.queue_depth_peak = max(self.stats.queue_depth_peak,
                                              self._q.qsize())
        with tenant.lock:
            tenant.submitted += 1
        return ticket

    def scan(self, request: ScanRequest, timeout: "float | None" = None):
        """Submit + wait: the one-call form."""
        return self.submit(request).result(timeout)

    # -- workers ---------------------------------------------------------------

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            ticket, request, t_submit, session = item
            tenant = self.tenants.get(request.tenant)
            t_start = time.perf_counter()
            wait = t_start - t_submit
            ticket.queue_wait_s = wait
            self._hist_wait.record(wait)
            trace = getattr(ticket.token, "trace", None)
            prev_trace = None
            if trace is not None:
                trace.add_timed("queue_wait", t_submit, t_start)
                # install as this worker thread's request trace: cache
                # probes and device dispatch deep in the call tree find it
                # without a token in hand
                prev_trace = set_request_trace(trace)
            first = request.paths[0] if request.paths else None
            with self._inflight_lock:
                self._inflight[ticket.id] = (str(first), t_start)
            rows = 0
            yielded = False
            try:
                # a request that expired (or was cancelled) while queued
                # fails HERE, typed, before any byte is charged or read
                ticket.token.check()
                if session is not None:
                    # the session IS the response: the caller's result()
                    # unblocks with it now, batches flow as they decode.
                    # Under stream-aware fair scheduling the session hands
                    # this slot back between batches whenever another
                    # tenant has queued work; otherwise it occupies the
                    # slot until it drains, errors, or is cancelled.
                    if not ticket.done():
                        ticket._finish(result=session)
                    ycheck = None
                    if self._stream_yield and tenant is not None:
                        tname = tenant.name
                        ycheck = (lambda _t=tname:
                                  self._q.has_other_waiters(_t))
                    finished = session._produce(yield_check=ycheck)
                    if not finished and self._closed:
                        # closed while mid-yield: requeueing would strand
                        # the session behind the shutdown sentinels
                        exc0 = CancelledError(
                            "scan service closed; streaming session "
                            "terminated")
                        session._abort(exc0)
                        raise exc0
                    yielded = not finished
                    rows = session.rows_emitted
                    result, exc = session, None
                else:
                    result, exc = self._execute(request, ticket.token), None
                    rows = _count_rows(result)
            except BaseException as e:  # noqa: BLE001 — delivered to caller
                result, exc = None, e
                yielded = False
                # a continuation leg's ticket already resolved to the
                # session — its failure must reach the consumer through
                # the session buffer (first verdict wins; idempotent)
                if session is not None and ticket.done():
                    session._fail(e)
            if yielded:
                # mid-stream slot yield: book this leg's seconds, hand the
                # slot back, requeue the session as a fresh arrival (DRR
                # charges the tenant's deficit again — batch-granular
                # fairness).  No completion bookkeeping: the stream is
                # still live and a later leg finishes it.
                t_end = time.perf_counter()
                if trace is not None:
                    set_request_trace(prev_trace)
                with self._inflight_lock:
                    self._inflight.pop(ticket.id, None)
                with self.stats.lock:
                    self.stats.queue_wait_seconds += wait
                    self.stats.exec_seconds += t_end - t_start
                    self.stats.stream_yields += 1
                with tenant.lock:
                    tenant.queue_wait_seconds += wait
                    tenant.exec_seconds += t_end - t_start
                self._q.requeue(tenant.name, tenant.weight,
                                (ticket, request, time.perf_counter(),
                                 session))
                continue
            # ALL bookkeeping lands before _finish sets the ticket's event:
            # a caller waking from result() must read final exec_s/stats,
            # never a zero the worker hadn't written yet
            t_end = time.perf_counter()
            ticket.exec_s = t_end - t_start
            retained = False
            if trace is not None:
                set_request_trace(prev_trace)
                if exc is not None:
                    trace.mark_error(exc)
                    if isinstance(exc, DeadlineExceededError):
                        trace.set_flag("deadline")
                    elif isinstance(exc, CancelledError):
                        trace.set_flag("cancelled")
                    elif isinstance(exc, OverloadError):
                        trace.set_flag("shed")
                trace.finish()
                retained = self.sampler.offer(trace,
                                              duration_s=t_end - t_submit,
                                              error=exc is not None)
            # exemplars only name RETAINED traces — a percentile's example
            # must be fetchable back via `pq_tool trace --request`
            ex = trace.trace_id if retained else None
            self._hist_exec.record(ticket.exec_s, exemplar=ex)
            self._hist_total.record(t_end - t_submit, exemplar=ex)
            if tenant is not None:
                tenant.hist.record(t_end - t_submit, exemplar=ex)
            with self._inflight_lock:
                self._inflight.pop(ticket.id, None)
                self._streams.pop(ticket.id, None)
            with self.stats.lock:
                self.stats.queue_wait_seconds += wait
                self.stats.exec_seconds += ticket.exec_s
                if exc is not None:
                    self.stats.failed += 1
                    if isinstance(exc, DeadlineExceededError):
                        self.stats.deadline_exceeded += 1
                    elif isinstance(exc, CancelledError):
                        self.stats.cancelled += 1
                else:
                    self.stats.completed += 1
                    self.stats.rows += rows
            with tenant.lock:
                tenant.queue_wait_seconds += wait
                tenant.exec_seconds += ticket.exec_s
                if retained:
                    tenant.traces_retained += 1
                if exc is not None:
                    tenant.failed += 1
                else:
                    tenant.completed += 1
                    tenant.rows += rows
            # a streaming ticket already resolved to its session; its
            # producer's failure was delivered through the session buffer
            if not ticket.done():
                if exc is not None:
                    ticket._finish(exc=exc)
                else:
                    ticket._finish(result=result)

    def _fold_io(self, store) -> None:
        """Bank a closing store's IOStats into the service aggregate (the
        registry io section's durable half) and drop it from the live
        view so obs_registry never double-counts it."""
        from ..obs import _merge_num_tree

        d = store.stats.as_dict()
        self._served_stores.discard(store)
        with self._io_agg_lock:
            _merge_num_tree(self._io_agg, d)

    def _charge_stream(self, tenant, nbytes: int, token) -> tuple:
        """Charge ``nbytes`` against the tenant's budget SLICE first, then
        the global budget (each clamped to its own cap, mirroring the
        one-shot path's oversized-item rule).  Tenant-first ordering is
        the fairness property: a tenant over its slice blocks HERE, on
        its own budget, without ever holding global bytes a neighbor
        needs.  Returns the (tenant, global) charges for release."""
        tc = gc = 0
        tb = tenant.budget if tenant is not None else None
        if tb is not None and tb.max_bytes > 0:
            tc = min(int(nbytes), tb.max_bytes)
            if tc:
                tb.acquire(tc, cancel=token)
        if self._budget.max_bytes > 0:
            gc = min(int(nbytes), self._budget.max_bytes)
            if gc:
                try:
                    self._budget.acquire(gc, cancel=token)
                except BaseException:
                    if tc:
                        tb.release(tc)
                    raise
        return (tc, gc)

    def _release_stream(self, tenant, charges: tuple) -> None:
        tc, gc = charges
        if gc:
            self._budget.release(gc)
        if tc and tenant is not None:
            tenant.budget.release(tc)

    def _resolve_filter(self, request: ScanRequest):
        flt = request.filter
        if isinstance(flt, str):
            from ..predicate import parse_filter

            return parse_filter(flt)
        return flt

    def _execute(self, request: ScanRequest,
                 token: "CancelToken | None" = None) -> dict:
        """Run one request over the shared cache: per file, gate on the
        file's circuit breaker, read the footer/plan through the cache,
        charge the plan's byte estimate against the admission budget, then
        scan with a plan-replaying reader carrying the request's cancel
        token.  Returns ``{path: {column: ColumnData}}`` in request order.

        Classified failures (transport exhaustion, malformed data, wedges)
        are noted against the file's breaker so a persistently-failing
        file fast-fails future requests; deadline/cancel verdicts are NOT
        — an impatient caller never opens a healthy file's circuit."""
        from ..reader import FileReader

        pred = self._resolve_filter(request)
        tenant = self.tenants.get(request.tenant)
        trace = getattr(token, "trace", None) if token is not None else None
        out: dict = {}
        for path in request.paths:
            if token is not None:
                token.check()  # file boundary: stop before the next file
            key = self.cache.file_key(path)
            bkey = key if key is not None else ("path", str(path))
            self.breakers.admit(bkey, str(path))
            try:
                meta, schema = self.cache.footer(path)
                plan = self.cache.plan(key, request.columns, pred,
                                       meta=meta, schema=schema)
                vcrc = (request.validate_crc
                        if request.validate_crc is not None
                        else self._validate_crc)
                # the decoded-result tier (serve/result_cache.py), bound
                # through the ONE gate PlanCache.bind_results encodes
                rcache = self.cache.bind_results(
                    key, plan, row_filter=pred, device=request.device,
                    validate_crc=vcrc, tenant=tenant.name)
                with _span(trace, "cache_probe", path=str(path)):
                    served = (self._serve_from_cache(rcache, plan, request,
                                                     token, tenant)
                              if rcache is not None else None)
                    if trace is not None:
                        trace.annotate(hit=served is not None)
                if served is not None:
                    # pure cache hit: no reader, no store, no device
                    # dispatch — the file's breaker still notes the success
                    out[str(path)] = served
                    self.breakers.note(bkey, str(path), ok=True)
                    continue
                # admission wait: the budget acquire is where a request
                # blocks behind its tenant's slice or the global pool
                with _span(trace, "admission",
                           estimated_bytes=plan.estimated_bytes()):
                    charges = self._charge_stream(
                        tenant, plan.estimated_bytes(), token)
                try:
                    kw = dict(columns=request.columns, metadata=meta,
                              row_filter=pred, prefetch=request.prefetch,
                              validate_crc=vcrc,
                              store=self._store, plan=plan,
                              dict_cache=BoundDictCache(self.cache, key),
                              result_cache=rcache,
                              cancel=token)
                    with _span(trace, "read", path=str(path),
                               device=request.device):
                        if request.device:
                            from ..device_reader import DeviceFileReader

                            with DeviceFileReader(path, hang_s=self._hang_s,
                                                  **kw) as r:
                                cols: dict = {}
                                for group in r.iter_row_groups():
                                    for name, cd in group.items():
                                        cols.setdefault(name,
                                                        []).append(cd)
                                out[str(path)] = {
                                    name: (parts[0] if len(parts) == 1
                                           else parts)
                                    for name, parts in cols.items()}
                        else:
                            with FileReader(path, **kw) as r:
                                out[str(path)] = self._read_watched(r)
                finally:
                    self._release_stream(tenant, charges)
            except _CLASSIFIED_FAILURES:
                self.breakers.note(bkey, str(path), ok=False)
                raise
            self.breakers.note(bkey, str(path), ok=True)
        return out

    def _serve_from_cache(self, rcache, plan, request: ScanRequest,
                          token, tenant=None) -> "dict | None":
        """The result-cache hit path: when EVERY (surviving row group,
        selected column) unit of the plan is cached under this request's
        decode signature, assemble the response straight from the cache —
        zero ``ByteStore`` reads, zero device dispatches, no reader at all.

        Admission accounting (ISSUE 14 satellite): the hit path charges
        the ACTUAL cached decoded size against the shared budget, not the
        plan's full-decode estimate — hot traffic must not queue behind a
        phantom charge for work it will never do.  Returns None on any
        missing unit (the reader path decodes and populates)."""
        ordinals = plan.selected_ordinals()
        columns = plan.columns
        if not ordinals or not columns:
            return None
        # response dict order must match the reader path's (footer chunk
        # order — plan.columns is SORTED): a consumer must never see the
        # same request's columns transposed by cache temperature
        rgp = next((r for r in plan.row_groups if r.ordinal == ordinals[0]),
                   None)
        ordered = ([cp.column for cp in rgp.chunks] if rgp is not None
                   else list(columns))
        if set(ordered) != set(columns):
            ordered = list(columns)
        columns = ordered
        units = [rcache._full(rg, c) for rg in ordinals for c in columns]
        got = rcache.cache.lookup_units(units)
        if got is None:
            return None
        total = sum(n for _v, n in got)
        charges = self._charge_stream(tenant, total, token)
        try:
            per_col: dict = {}
            vals = iter(got)
            for _rg in ordinals:
                for c in columns:
                    per_col.setdefault(c, []).append(next(vals)[0])
            if request.device:
                return {c: parts[0] if len(parts) == 1 else parts
                        for c, parts in per_col.items()}
            from ..reader import _concat_column_data

            return {c: (parts[0] if len(parts) == 1
                        else _concat_column_data(parts))
                    for c, parts in per_col.items()}
        finally:
            self._release_stream(tenant, charges)

    def _read_watched(self, r) -> dict:
        """``read_all`` under a per-request watchdog: a stalled store fetch
        (the transport wedge) dumps flight state and aborts THIS request
        with HangError while every other worker keeps serving.  Mirrors
        DeviceFileReader's own watchdog wiring — the host FileReader has
        none of its own."""
        from ..obs import Watchdog

        wd = Watchdog(resolve_hang_s(self._hang_s))
        if not wd.enabled or r._store.stats is None:
            # a plain local store cannot stall (os.pread either returns or
            # errors), and its counters don't tick on the sequential path —
            # arming the dog there would misread a long clean read as a
            # wedge.  Stall containment is for instrumented range stores.
            return r.read_all()
        wd.watch("pipeline", lambda: r._pipe_stats.sample())
        wd.watch("iostore", r._store.stats.progress)
        wd.add_abort_hook(r._store.abort)
        # ALSO poison the request's own cancel token: on a SHARED store a
        # neighbor's begin_scan legitimately clears the store-wide abort,
        # but this request's unit boundaries must still observe the wedge
        # verdict and stop
        if r._cancel is not None:
            wd.add_abort_hook(r._cancel.cancel)
        wd.start()
        try:
            out = r.read_all()
            wd.check()  # surface a fired raise-policy HangError
            return out
        finally:
            wd.stop()

    # -- lifecycle -------------------------------------------------------------

    def close(self) -> None:
        """Drain-free shutdown: queued-but-unstarted requests fail with
        OverloadError; executing one-shot requests finish; LIVE streaming
        sessions are aborted — their producers stop at the next batch
        boundary, buffered batches release their budget bytes, and a
        consumer blocked in ``next()`` raises the terminal
        :class:`~tpu_parquet.errors.CancelledError` promptly (no worker
        thread leaks behind an abandoned session)."""
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
        for item in self._q.drain():
            ticket, _req, _t, session = item
            with self.stats.lock:
                # accounted as rejections so the serve section always
                # reconciles: submitted == completed + failed + rejected
                self.stats.rejected += 1
            exc = OverloadError(
                "scan service closed before this request started")
            if session is not None:
                with self._inflight_lock:
                    self._streams.pop(ticket.id, None)
                session._abort(exc)
            # a yielded streaming continuation's ticket already resolved
            # to its session — the abort above delivered the verdict; a
            # re-finish would clobber the caller's result
            if not ticket.done():
                ticket._finish(exc=exc)
        with self._inflight_lock:
            live = list(self._streams.values())
        for session in live:
            session._abort(CancelledError(
                "scan service closed; streaming session terminated"))
        for _ in self._workers:
            self._q.put_sentinel()
        for t in self._workers:
            t.join(timeout=60)
        self._dumper.stop()
        self._spool.stop()

    def __enter__(self) -> "ScanService":
        return self

    def __exit__(self, *exc):
        self.close()

    # -- reporting -------------------------------------------------------------

    def get_trace(self, trace_id: str) -> "dict | None":
        """A retained trace tree by id (exemplar trace ids resolve here —
        the ``pq_tool trace --request`` backend)."""
        return self.sampler.get(trace_id)

    def trace_dump(self, path: str) -> str:
        """Write every retained trace tree to ``path`` (the versioned
        dump ``pq_tool trace --request`` reads offline)."""
        return self.sampler.dump(path)

    def sample(self) -> dict:
        """Live admission state (flight dumps + obs.Sampler track): queue
        depth, in-flight requests with ages, and the cache counters."""
        now = time.perf_counter()
        with self._inflight_lock:
            inflight = {str(rid): {"path": p, "age_s": round(now - t0, 6)}
                        for rid, (p, t0) in self._inflight.items()}
        oldest = max((v["age_s"] for v in inflight.values()), default=0.0)
        return {
            "queue_depth": self._q.qsize(),
            "in_flight": len(inflight),
            "oldest_request_s": oldest,
            "occupancy": round(self._occupancy(), 4),
            "brownout": self.brownout,
            "fair": self._q.fair,
            "tenant_queues": self._q.tenant_depths(),
            "streams": len(self._streams),
            "requests": inflight,
            "cache": self.cache.counters(),
            "result_cache": self.cache.results.counters(),
            # open circuits by file, oldest first — the autopsy/doctor
            # `circuit-open` evidence rides every flight dump
            "circuit_open": self.breakers.open_files(),
        }

    def serve_stats(self) -> dict:
        """The registry ``serve`` section: counters + cache counters +
        circuit-breaker transitions + the per-tenant ``tenants`` subtree
        (weights, lifecycle flows, shed counters, budget slices, and each
        tenant's resident result-cache bytes)."""
        tenants = {}
        for name, t in self.tenants.tenants().items():
            d = t.as_dict()
            d["cache_held_bytes"] = self.cache.results.tenant_bytes(name)
            tenants[name] = d
        return {**self.stats.as_dict(), "cache": self.cache.counters(),
                "circuit": self.breakers.counters(), "tenants": tenants,
                "trace": self.sampler.counters()}

    def obs_registry(self):
        """Unified metrics tree: the ``serve`` section, the request
        latency histograms (``serve.queue_wait`` / ``serve.exec`` /
        ``serve.request`` — the p50/p95/p99 SLO surface), and the ``io``
        section folded from every store this service's requests read
        through (retries, hedges issued/won/wasted — the hedge
        effectiveness evidence doctor reads)."""
        from ..obs import StatsRegistry

        reg = StatsRegistry()
        reg.add_serve(self.serve_stats())
        # the tiered decoded-result cache's own section (per-tier hit/miss/
        # eviction/invalidation flows + byte gauges + single-flight waits)
        reg.add_cache(self.cache.results.counters())
        reg.histogram("serve.queue_wait").merge_from(self._hist_wait)
        reg.histogram("serve.exec").merge_from(self._hist_exec)
        reg.histogram("serve.request").merge_from(self._hist_total)
        # per-tenant end-to-end latency (the fairness SLO surface the
        # noisy-neighbor bench and `pq_tool serve-stats` read)
        for name, t in self.tenants.tenants().items():
            if t.hist.count:
                reg.histogram(f"serve.tenant.{name}").merge_from(t.hist)
        with self._io_agg_lock:
            if self._io_agg:
                reg.add_io(dict(self._io_agg))
        for st in list(self._served_stores):
            if st.stats is not None:
                reg.add_io(st.stats)
        # async fetch-engine counters (in-flight gauge, queue-wait) for
        # requests whose stores routed through the shared engine
        from ..iostore_async import fold_engine_stats
        fold_engine_stats(reg)
        return reg
