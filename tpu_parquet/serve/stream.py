"""StreamingScan: long-lived incremental batch sessions over the serve tier.

The one-shot ``ScanService`` request materializes its whole response before
the caller sees a row — the wrong shape for a training job that wants a
steady stream of fixed-shape batches from a multi-gigabyte file set.  A
``ScanRequest(stream=True, batch_rows=N)`` instead returns a
:class:`StreamingScan` session: the worker decodes row groups one at a
time and pushes **fixed-shape padded+masked batches** (the exact
``data.DataLoader`` batch/mask contract, via its shared
:func:`~tpu_parquet.data.loader.pad_and_mask` helper) through a bounded
buffer the consumer iterates.

Contracts the session inherits rather than reinvents:

- **Memory**: every buffered batch's bytes are charged to the tenant's
  :class:`~tpu_parquet.alloc.InFlightBudget` slice and the service's
  global budget BEFORE it is buffered, and released when the consumer
  takes it — a slow consumer backpressures its own producer (and only its
  own tenant's slice), never the fleet.  The buffer depth itself is
  bounded by ``TPQ_STREAM_BUFFER_BATCHES``.
- **Cancellation/deadline/breakers** (PR 11), at *batch* granularity: the
  request's :class:`~tpu_parquet.resilience.CancelToken` is checked at
  every group/batch boundary, classified failures note the file's circuit
  breaker exactly as the one-shot path does, and a blocked ``next()``
  caller receives the typed terminal verdict promptly.
- **Warm path** (PR 13): each row group is first probed in the decoded
  ``ResultCache``; a fully-cached group streams straight from the cached
  host ``ColumnData`` — structurally zero ``ByteStore`` reads and zero
  device dispatches for that batch (the reader is not even opened until
  the first cold group).  ``device=True`` sessions decode host-side and
  ship each batch with the loader's staging call — the per-batch ship is
  the product there, not overhead.
- **Resumability**: :meth:`StreamingScan.cursor` snapshots the consumer's
  position as a versioned ``b"TPQS"`` blob under the same discipline as
  the ``TPQL`` loader checkpoint (strict validation, version echo,
  fingerprint refusal via :func:`check_cursor_compatible`) — save →
  resume (``ScanRequest(cursor=blob)``) → iterate is bit-identical to the
  uninterrupted stream, because batches never span files and the cursor
  only ever lands on batch boundaries.
"""

from __future__ import annotations

import hashlib
import json
import queue
import threading
import time

import numpy as np

from ..errors import CheckpointError, ParquetError
from ..obs import env_int

__all__ = ["CURSOR_MAGIC", "CURSOR_VERSION", "StreamingScan",
           "check_cursor_compatible", "pack_cursor", "request_digest",
           "unpack_cursor", "validate_cursor"]

CURSOR_VERSION = 1
CURSOR_MAGIC = b"TPQS"

# consumer-side poll tick while blocked on an empty buffer: bounds how long
# a terminal verdict (cancel/deadline/close) can go unnoticed by a blocked
# next() caller
_POLL_S = 0.05

# (key, lo, hi) rails, same scheme as data/checkpoint.py: a mutated blob
# cannot smuggle astronomically large ints into the resume arithmetic
_INT_FIELDS = (
    ("version", CURSOR_VERSION, CURSOR_VERSION + 1),
    ("batch_rows", 1, 1 << 40),
    ("n_paths", 1, 1 << 32),
    ("path_index", 0, 1 << 32),
    ("rows_done", 0, 1 << 62),
    ("batches_emitted", 0, 1 << 62),
)
_BOOL_FIELDS = ("device",)

# the config half of the cursor: must match the resuming request exactly
# (the cursor half — path_index/rows_done — is what resume ADOPTS).
# request_digest hashes the ordered paths + projection + filter text, so a
# cursor saved against one request shape refuses any other.
_FINGERPRINT = ("batch_rows", "device", "n_paths", "request_digest")


def request_digest(request) -> str:
    """Stable fingerprint of a streaming request's *shape* (ordered paths,
    projection, filter, device, batch geometry) — the refusal rail that
    keeps a saved cursor from seeking a different stream.  File CONTENT is
    deliberately not hashed: generation invalidation is the PlanCache's
    job; the cursor pins what the caller asked for."""
    flt = request.filter
    if flt is not None and not isinstance(flt, str):
        from ..scanplan import predicate_fingerprint

        flt = predicate_fingerprint(flt) or "opaque-predicate"
    cols = (None if request.columns is None
            else [str(c) for c in request.columns])
    canon = json.dumps({
        "paths": [str(p) for p in request.paths],
        "columns": cols,
        "filter": flt,
        "device": bool(request.device),
        "batch_rows": int(request.batch_rows),
    }, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode("utf-8")).hexdigest()[:16]


def _int_field(state: dict, key: str, lo: int, hi: int) -> int:
    v = state.get(key)
    if type(v) is not int:  # bool is an int subclass: excluded on purpose
        raise CheckpointError(
            f"stream cursor field {key!r} must be an int, "
            f"got {type(v).__name__}")
    if not lo <= v < hi:
        raise CheckpointError(
            f"stream cursor field {key!r} = {v} outside [{lo}, {hi})")
    return v


def validate_cursor(state) -> dict:
    """Strict structural validation; returns ``state`` or raises
    :class:`~tpu_parquet.errors.CheckpointError`."""
    if not isinstance(state, dict):
        raise CheckpointError(
            f"stream cursor must be a dict, got {type(state).__name__}")
    for key, lo, hi in _INT_FIELDS:
        _int_field(state, key, lo, hi)
    for key in _BOOL_FIELDS:
        if type(state.get(key)) is not bool:
            raise CheckpointError(
                f"stream cursor field {key!r} must be a bool")
    if state["path_index"] > state["n_paths"]:
        raise CheckpointError(
            f"stream cursor path_index {state['path_index']} past its "
            f"{state['n_paths']} paths")
    # the consumer only ever lands on batch boundaries (a padded tail
    # advances path_index and zeroes rows_done): anything else is a
    # tampered blob whose adoption would shift every subsequent batch
    if state["rows_done"] % state["batch_rows"] != 0:
        raise CheckpointError(
            f"stream cursor rows_done {state['rows_done']} is not a batch "
            f"boundary (batch_rows {state['batch_rows']})")
    dg = state.get("request_digest")
    if type(dg) is not str or not (8 <= len(dg) <= 64):
        raise CheckpointError(
            "stream cursor field 'request_digest' must be a short hex "
            "string")
    return state


def pack_cursor(state: dict) -> bytes:
    """Serialize a validated cursor dict to the versioned blob
    (``b"TPQS" | version:u16be | json``)."""
    validate_cursor(state)
    payload = json.dumps(state, sort_keys=True,
                         separators=(",", ":")).encode("utf-8")
    return CURSOR_MAGIC + int(state["version"]).to_bytes(2, "big") + payload


def unpack_cursor(blob) -> dict:
    """Parse + validate a cursor blob; raises CheckpointError on anything
    off (truncation, bad magic, unknown version, type/range violations,
    header/payload version disagreement)."""
    if isinstance(blob, dict):  # already-unpacked cursors pass validated
        return validate_cursor(blob)
    if not isinstance(blob, (bytes, bytearray, memoryview)):
        raise CheckpointError(
            f"stream cursor blob must be bytes, got {type(blob).__name__}")
    blob = bytes(blob)
    if len(blob) < len(CURSOR_MAGIC) + 2 or \
            blob[: len(CURSOR_MAGIC)] != CURSOR_MAGIC:
        raise CheckpointError("not a stream cursor blob (bad magic)")
    version = int.from_bytes(
        blob[len(CURSOR_MAGIC): len(CURSOR_MAGIC) + 2], "big")
    if version != CURSOR_VERSION:
        raise CheckpointError(
            f"unsupported stream cursor version {version} "
            f"(this build reads {CURSOR_VERSION})")
    try:
        state = json.loads(blob[len(CURSOR_MAGIC) + 2:].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise CheckpointError(f"corrupt stream cursor payload: {e}") from e
    state = validate_cursor(state)
    if state["version"] != version:
        raise CheckpointError("stream cursor version header/payload mismatch")
    return state


def check_cursor_compatible(state: dict, expected: dict) -> None:
    """Refuse a cursor whose config fingerprint differs from the resuming
    request's — a mismatch means it describes a DIFFERENT stream and
    adopting its position would silently yield wrong rows."""
    for key in _FINGERPRINT:
        got, want = state.get(key), expected[key]
        if got != want:
            raise CheckpointError(
                f"stream cursor mismatch on {key!r}: cursor has {got!r}, "
                f"this request has {want!r}")


def _column_rows(cd, column: str) -> np.ndarray:
    """One decoded column chunk as a per-row array the batcher can slice:
    fixed-width columns pass through as their numpy values; BYTE_ARRAY
    columns become object arrays of ``bytes``.  Nested or nullable
    columns are not streamable (the DataLoader carries the same
    constraint) — refusing here keeps padded shapes honest."""
    from ..column import ByteArrayData

    if getattr(cd, "rep_levels", None) is not None:
        raise ParquetError(
            f"streaming scan: column {column!r} is nested (rep levels "
            f"present) — not batchable to a fixed shape")
    values = cd.values
    if values is None:
        raise ParquetError(f"streaming scan: column {column!r} decoded no "
                           f"values")
    if isinstance(values, ByteArrayData):
        arr = np.array(values.to_list(), dtype=object)
    else:
        arr = np.asarray(values)
    dl = getattr(cd, "def_levels", None)
    if dl is not None and len(arr) != len(dl):
        raise ParquetError(
            f"streaming scan: column {column!r} has nulls — not batchable "
            f"to a fixed shape")
    return arr


def _batch_nbytes(batch: dict) -> int:
    """Accounting size of one assembled batch (object arrays of byte
    strings count their payload, not just the pointer array)."""
    n = 0
    for a in batch.values():
        nb = int(getattr(a, "nbytes", 0) or 0)
        if getattr(a, "dtype", None) == object:
            nb += sum(len(v) for v in a if isinstance(v, (bytes, str)))
        n += nb
    return max(n, 1)


class StreamingScan:
    """One live streaming session: iterate it for batches, ``cursor()``
    to snapshot the position, ``close()``/``cancel()`` to stop early
    (context manager supported).

    The producer half runs on the service worker that picked the request
    up (a streaming session OCCUPIES its worker slot for its lifetime —
    size ``TPQ_SERVE_CONCURRENCY`` for the number of concurrent streams);
    the consumer half is whoever iterates.  All cross-thread state flows
    through the bounded buffer plus a terminal-verdict latch."""

    def __init__(self, service, request, ticket, tenant,
                 resume_state: "dict | None" = None):
        self._service = service
        self.request = request
        self.ticket = ticket
        self.token = ticket.token
        self._tenant = tenant
        self.batch_rows = int(request.batch_rows)
        if self.batch_rows < 1:
            raise ParquetError(
                f"streaming scan: batch_rows must be >= 1, "
                f"got {request.batch_rows}")
        depth = env_int("TPQ_STREAM_BUFFER_BATCHES", 2, lo=1)
        self._buf: "queue.Queue" = queue.Queue(maxsize=depth)
        self._lock = threading.Lock()
        self._terminal: "BaseException | None" = None
        self._exhausted = False
        self._digest = request_digest(request)
        # consumer-side cursor (what cursor() snapshots): advanced only
        # when a batch is actually DELIVERED — buffered-but-untaken work
        # is not part of the position
        self._cur_path = resume_state["path_index"] if resume_state else 0
        self._cur_rows = resume_state["rows_done"] if resume_state else 0
        self._batches_taken = (resume_state["batches_emitted"]
                               if resume_state else 0)
        self._resume = resume_state
        # the persistent producer generator (stream-aware fair scheduling:
        # a worker can park the session mid-file and a later leg — on any
        # worker — resumes exactly where the last batch left off)
        self._gen = None
        # structural warm/cold accounting (tests + serve stats)
        self.warm_batches = 0
        self.cold_groups = 0
        self.warm_groups = 0
        self.rows_emitted = 0
        self.slot_yields = 0
        # a cancel flips the terminal latch immediately — a blocked
        # next() caller sees the verdict on its next poll tick instead of
        # only at the producer's next boundary
        self.token.on_cancel(self._note_terminal)

    # -- consumer half ---------------------------------------------------------

    def __iter__(self) -> "StreamingScan":
        return self

    def __next__(self) -> dict:
        if self._exhausted:
            raise StopIteration
        while True:
            try:
                kind, payload, meta = self._buf.get(timeout=_POLL_S)
            except queue.Empty:
                with self._lock:
                    term = self._terminal
                if term is not None:
                    self._exhausted = True
                    raise term
                self.token.check()
                continue
            if kind == "end":
                with self._lock:
                    self._cur_path = len(self.request.paths)
                    self._cur_rows = 0
                self._exhausted = True
                raise StopIteration
            if kind == "error":
                self._exhausted = True
                raise payload
            self._service._release_stream(self._tenant, meta["charges"])
            with self._lock:
                if meta["file_done"]:
                    self._cur_path = meta["path_index"] + 1
                    self._cur_rows = 0
                else:
                    self._cur_path = meta["path_index"]
                    self._cur_rows = meta["rows_done"]
                self._batches_taken += 1
            if self._tenant is not None:
                with self._tenant.lock:
                    self._tenant.stream_batches += 1
            stats = self._service.stats
            with stats.lock:
                stats.stream_batches += 1
            return payload

    def cursor(self) -> bytes:
        """The resumable position blob: feed it back as
        ``ScanRequest(cursor=...)`` (same paths/columns/filter/device/
        batch_rows — :func:`check_cursor_compatible` refuses anything
        else) and iteration continues bit-identically from the next
        undelivered batch."""
        with self._lock:
            state = {
                "version": CURSOR_VERSION,
                "batch_rows": self.batch_rows,
                "n_paths": len(self.request.paths),
                "path_index": self._cur_path,
                "rows_done": self._cur_rows,
                "batches_emitted": self._batches_taken,
                "device": bool(self.request.device),
                "request_digest": self._digest,
            }
        return pack_cursor(state)

    def cancel(self) -> None:
        """Stop the stream (idempotent): the producer halts at its next
        boundary, buffered batches are discarded with their budget bytes
        released, and further ``next()`` raises the terminal
        :class:`~tpu_parquet.errors.CancelledError`."""
        self.ticket.cancel()
        self._drain_release()

    def close(self) -> None:
        self.cancel()

    def __enter__(self) -> "StreamingScan":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- terminal delivery -----------------------------------------------------

    def _note_terminal(self, exc: BaseException) -> None:
        with self._lock:
            if self._terminal is None:
                self._terminal = exc

    def _fail(self, exc: BaseException) -> None:
        """Producer-side failure delivery: latch the verdict and try to
        queue it BEHIND already-buffered batches (the consumer drains good
        work first, then sees the typed error).  First verdict wins —
        a later failure of an already-terminal session is not re-queued."""
        with self._lock:
            already = self._terminal is not None
            if not already:
                self._terminal = exc
        if already:
            return
        try:
            self._buf.put_nowait(("error", exc, None))
        except queue.Full:
            pass  # the empty-buffer terminal check delivers it instead

    def _abort(self, exc: BaseException) -> None:
        """Service-shutdown path: cancel, latch, release every buffered
        batch's budget bytes.  A consumer blocked in ``next()`` raises
        ``exc`` within one poll tick."""
        self.token.cancel(exc)
        self._note_terminal(exc)
        self._close_gen()
        self._drain_release()

    def _close_gen(self) -> None:
        """Release a parked producer generator's resources (its open
        FileReader).  A generator mid-``next()`` on a worker cannot be
        closed from here — the cancelled token stops it at its next
        boundary instead."""
        gen = self._gen
        self._gen = None
        if gen is not None:
            try:
                gen.close()
            except (ValueError, RuntimeError):
                pass  # executing on a worker right now

    def _drain_release(self) -> None:
        while True:
            try:
                kind, _payload, meta = self._buf.get_nowait()
            except queue.Empty:
                return
            if kind == "batch":
                self._service._release_stream(self._tenant, meta["charges"])

    # -- producer half (runs on the service worker) ----------------------------

    def _push(self, item, token) -> None:
        """Blocking buffer put that stays cancellable: the producer parked
        behind a slow consumer still honors deadline/cancel promptly."""
        while True:
            token.check()
            try:
                self._buf.put(item, timeout=_POLL_S)
                return
            except queue.Full:
                continue

    def _emit(self, token, path_index: int, cols: dict, n: int,
              rows_done: int, file_done: bool) -> None:
        """Assemble one fixed-shape batch (pad+mask, optional device ship),
        charge its bytes, and buffer it."""
        from ..data.loader import pad_and_mask, ship_to_device

        trace = getattr(token, "trace", None)
        t0 = time.perf_counter() if trace is not None else 0.0
        batch = pad_and_mask(cols, n, self.batch_rows, mask_key="mask")
        if self.request.device:
            try:
                batch = ship_to_device(batch)
            except TypeError as e:
                raise ParquetError(
                    f"streaming scan: batch is not device-shippable "
                    f"(object-dtype column?): {e}") from e
        nbytes = _batch_nbytes(batch)
        charges = self._service._charge_stream(self._tenant, nbytes, token)
        meta = {"path_index": path_index, "rows_done": rows_done,
                "file_done": file_done, "charges": charges}
        try:
            self._push(("batch", batch, meta), token)
        except BaseException:
            self._service._release_stream(self._tenant, charges)
            raise
        self.rows_emitted += n
        if trace is not None:
            # closed after the fact: includes assembly + (device) ship +
            # the buffer wait behind a slow consumer
            trace.add_timed("batch", t0, time.perf_counter(), rows=n,
                            nbytes=nbytes, path_index=path_index,
                            file_done=file_done)

    def _produce(self, yield_check=None) -> bool:
        """Drive the producer: per file, per surviving row group, decode
        (or serve warm), slice into fixed-row batches, buffer.

        With ``yield_check`` set (stream-aware fair scheduling), the leg
        parks after any emitted batch for which ``yield_check()`` is true
        and returns ``False`` — the worker requeues the session and a
        later leg resumes the SAME generator (same reader, same pending
        remainder) exactly where it left off.  Returns ``True`` when the
        stream is fully produced.  Exceptions propagate to the worker
        (which counts them) after being delivered to the consumer via
        :meth:`_fail`."""
        try:
            gen = self._gen
            if gen is None:
                gen = self._gen = self._produce_gen()
            while True:
                try:
                    next(gen)
                except StopIteration:
                    self._gen = None
                    return True
                if yield_check is not None and yield_check():
                    self.slot_yields += 1
                    return False
        except BaseException as e:  # noqa: BLE001 — delivered to consumer
            self._gen = None
            self._fail(e)
            raise

    def _produce_gen(self):
        """The producer generator: yields once per emitted batch (the
        slot-yield boundaries)."""
        token = self.token
        req = self.request
        start = self._cur_path if self._resume is not None else 0
        skip = self._cur_rows if self._resume is not None else 0
        for pi in range(start, len(req.paths)):
            token.check()
            yield from self._stream_file(pi, req.paths[pi], skip)
            skip = 0
        self._push(("end", None, None), token)

    def _stream_file(self, path_index: int, path, skip_rows: int):
        """Stream one file (generator: yields after every emitted batch —
        the slot-yield boundaries): warm groups straight from the result
        cache, cold groups through a lazily-opened plan-replaying
        FileReader.  ``skip_rows`` (resume) skips whole groups by plan row
        counts — no IO, no decode — then slices into the first partial
        group."""
        from ..reader import FileReader
        from .cache import BoundDictCache
        from .service import _CLASSIFIED_FAILURES

        svc = self._service
        token = self.token
        req = self.request
        bs = self.batch_rows
        key = svc.cache.file_key(path)
        bkey = key if key is not None else ("path", str(path))
        svc.breakers.admit(bkey, str(path))
        reader = None
        try:
            meta, schema = svc.cache.footer(path)
            pred = svc._resolve_filter(req)
            plan = svc.cache.plan(key, req.columns, pred,
                                  meta=meta, schema=schema)
            vcrc = (req.validate_crc if req.validate_crc is not None
                    else svc._validate_crc)
            # host decode signature always: streaming decodes host-side
            # (device sessions ship per batch), so warm batches come from
            # the same entries a one-shot host scan populates
            rcache = svc.cache.bind_results(key, plan, row_filter=pred,
                                            device=False, validate_crc=vcrc,
                                            tenant=getattr(self._tenant,
                                                           "name", None))
            ordinals = plan.selected_ordinals()
            columns = self._ordered_columns(plan, ordinals)
            if "mask" in columns:
                raise ParquetError(
                    "streaming scan: a projected column is named 'mask' — "
                    "it would collide with the batch validity mask")
            nrows = {r.ordinal: int(r.num_rows) for r in plan.row_groups}
            pend: "dict[str, list]" = {c: [] for c in columns}
            pend_n = 0
            pend_cold = False
            consumed = 0   # surviving rows walked (skip arithmetic)
            emitted = skip_rows  # rows delivered so far within this file
            trace = getattr(token, "trace", None)
            for rg in ordinals:
                token.check()
                nr = nrows.get(rg, 0)
                if nr <= 0:
                    continue
                if consumed + nr <= skip_rows:
                    consumed += nr  # wholly before the cursor: no decode
                    continue
                t_g = time.perf_counter() if trace is not None else 0.0
                got = rcache.lookup_group(rg, columns) \
                    if rcache is not None else None
                if got is not None:
                    arrays = {c: _column_rows(got[c], c) for c in columns}
                    self.warm_groups += 1
                    cold = False
                else:
                    if reader is None:
                        reader = FileReader(
                            path, columns=req.columns, metadata=meta,
                            row_filter=pred, prefetch=req.prefetch,
                            validate_crc=vcrc, store=svc._store, plan=plan,
                            dict_cache=BoundDictCache(svc.cache, key),
                            result_cache=rcache, cancel=token)
                    group = reader.read_row_group(rg,
                                                  prefetch=req.prefetch)
                    arrays = {c: _column_rows(group[c], c) for c in columns}
                    self.cold_groups += 1
                    cold = True
                if trace is not None:
                    trace.add_timed("group", t_g, time.perf_counter(),
                                    rg=rg, rows=nr, warm=not cold,
                                    path=str(path))
                lens = {len(a) for a in arrays.values()}
                if len(lens) != 1 or lens != {nr}:
                    raise ParquetError(
                        f"streaming scan: row group {rg} column lengths "
                        f"{sorted(lens)} disagree with plan rows {nr}")
                lo = max(skip_rows - consumed, 0)
                consumed += nr
                if lo:
                    if lo >= nr:
                        continue
                    arrays = {c: a[lo:] for c, a in arrays.items()}
                take = nr - lo
                for c in columns:
                    pend[c].append(arrays[c])
                pend_n += take
                pend_cold = pend_cold or cold
                while pend_n >= bs:
                    cat = {c: (np.concatenate(pend[c])
                               if len(pend[c]) > 1 else pend[c][0])
                           for c in columns}
                    emitted += bs
                    pend_n -= bs
                    last_file_batch = (pend_n == 0
                                       and rg == ordinals[-1])
                    if not pend_cold:
                        self.warm_batches += 1
                    self._emit(token, path_index,
                               {c: a[:bs] for c, a in cat.items()}, bs,
                               emitted, last_file_batch)
                    pend = {c: ([cat[c][bs:]] if pend_n else [])
                            for c in columns}
                    # the carried remainder came from the group decoded
                    # LAST — its temperature is the remainder's
                    pend_cold = cold if pend_n else False
                    yield None  # slot-yield boundary (state is consistent)
            if pend_n:
                tail = {c: (np.concatenate(pend[c])
                            if len(pend[c]) > 1 else pend[c][0])
                        for c in columns}
                if not pend_cold:
                    self.warm_batches += 1
                self._emit(token, path_index, tail, pend_n, 0, True)
                yield None
        except _CLASSIFIED_FAILURES:
            self.breaker_note(bkey, path, ok=False)
            raise
        finally:
            if reader is not None:
                reader.close()
        self.breaker_note(bkey, path, ok=True)

    def breaker_note(self, bkey, path, ok: bool) -> None:
        self._service.breakers.note(bkey, str(path), ok=ok)

    @staticmethod
    def _ordered_columns(plan, ordinals) -> list:
        """Response column order = footer chunk order, exactly like the
        one-shot cache-hit path — a consumer must never see columns
        transposed by cache temperature or streaming mode."""
        columns = plan.columns
        rgp = next((r for r in plan.row_groups
                    if ordinals and r.ordinal == ordinals[0]), None)
        ordered = ([cp.column for cp in rgp.chunks] if rgp is not None
                   else list(columns))
        if set(ordered) != set(columns):
            ordered = list(columns)
        return ordered
