"""Multi-tenant fair-share QoS for the serve tier.

One ScanService carrying many tenants through a single FIFO queue has a
textbook failure mode: a noisy neighbor floods the queue and every other
tenant's latency becomes the flood's drain time.  PR 12's brownout sheds
*load*, but it sheds blindly — it cannot say "tenant A is the problem,
keep serving tenant B".  This module adds the per-tenant half:

- :class:`Tenant` — one tenant's identity, weight, counters, latency
  histogram, SLO target, and a *slice* of the service's in-flight memory
  budget (its own :class:`~tpu_parquet.alloc.InFlightBudget`, sized from
  its weight share so one tenant's giant scans backpressure that tenant,
  not the fleet).
- :class:`TenantRegistry` — the tenant table (``TPQ_SERVE_TENANTS``
  preconfigures ``name=weight`` pairs; unknown tenants auto-register at
  weight 1), budget-slice rebalancing, and the ``serve.tenants`` registry
  subtree.
- :class:`FairScheduler` — the admission queue: per-tenant sub-queues
  drained by deficit round-robin (quantum = weight, unit item cost), so a
  tenant with weight *w* gets *w* dequeues per cycle regardless of how
  deep any neighbor's backlog runs.  ``TPQ_SERVE_FAIR=0`` (or
  ``fair=False``) degrades it to global-FIFO ordering — the A/B the
  noisy-neighbor bench measures.

The scheduler preserves ScanService's admission contract exactly: one
global ``maxsize`` bound, ``put_nowait`` raising ``queue.Full`` at the
door, blocking ``get`` for workers, and ``None`` shutdown sentinels that
always outrank queued work.
"""

from __future__ import annotations

import json
import os
import queue
import threading
from collections import OrderedDict, deque

from ..alloc import InFlightBudget
from ..obs import LatencyHistogram, warn_env_once

__all__ = ["DEFAULT_TENANT", "FairScheduler", "Tenant", "TenantRegistry",
           "fair_enabled", "load_tenant_file", "parse_tenant_spec",
           "tenant_table"]

# requests that name no tenant all land here — single-tenant deployments
# never see tenancy at all (one queue, the whole budget, weight 1)
DEFAULT_TENANT = "default"


def fair_enabled(flag: "bool | None" = None) -> bool:
    """Resolve the fair-share switch: an explicit constructor flag wins,
    else ``TPQ_SERVE_FAIR`` (default ON — FIFO is the degraded A/B mode,
    not the product)."""
    if flag is not None:
        return bool(flag)
    return os.environ.get("TPQ_SERVE_FAIR", "1") != "0"


def load_tenant_file(path: str) -> "dict[str, dict]":
    """Parse a shared tenants.json (the ``TPQ_SERVE_TENANTS=@/path`` form
    — one tenant table every process in a fleet loads): ``{"name":
    weight_int | {"weight": w, "deadline_s": d, "slo_p99_ms": s}}``.
    Returns ``{name: {"weight", "deadline_s", "slo_p99_ms"}}``.  A
    missing/unreadable/malformed file degrades to an empty table via one
    :func:`warn_env_once` line, never raises — a bad shared config must
    not take a fleet member down."""
    try:
        with open(path) as f:
            raw = json.load(f)
        if not isinstance(raw, dict):
            raise ValueError("tenant table must be a JSON object")
    except (OSError, ValueError) as e:
        warn_env_once("TPQ_SERVE_TENANTS", f"@{path} ({e})", None)
        return {}
    out: "dict[str, dict]" = {}
    for name, cfg in raw.items():
        name = str(name).strip()
        if not name:
            continue
        if isinstance(cfg, bool):
            continue
        if isinstance(cfg, (int, float)):
            cfg = {"weight": cfg}
        if not isinstance(cfg, dict):
            continue  # a malformed entry, not a malformed table

        def fnum(key, lo=None, _cfg=cfg):
            v = _cfg.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                return None
            v = float(v)
            return v if (lo is None or v > lo) else None

        w = fnum("weight", lo=0)
        out[name] = {
            "weight": max(int(w), 1) if w is not None else 1,
            "deadline_s": fnum("deadline_s", lo=0),
            "slo_p99_ms": fnum("slo_p99_ms", lo=0),
        }
    return out


def tenant_table(spec: "str | None") -> "dict[str, dict]":
    """Resolve a ``TPQ_SERVE_TENANTS`` value — inline ``name=weight:
    deadline_s`` pairs or the ``@/path/to/tenants.json`` shared-file form
    — to ``{name: {"weight", "deadline_s", "slo_p99_ms"}}``."""
    if not spec:
        return {}
    spec = str(spec).strip()
    if spec.startswith("@"):
        return load_tenant_file(spec[1:])
    return {name: {"weight": w, "deadline_s": d, "slo_p99_ms": None}
            for name, (w, d) in _parse_inline_spec(spec).items()}


def parse_tenant_spec(spec: "str | None") -> "dict[str, tuple]":
    """Parse ``TPQ_SERVE_TENANTS``: ``"name=weight:deadline_s,..."``
    (weight optional, defaults 1, floored at 1; ``:deadline_s`` optional —
    a per-tenant default request deadline in seconds) or
    ``@/path/to/tenants.json`` (see :func:`load_tenant_file`).  Returns
    ``{name: (weight, deadline_s_or_None)}``.  Malformed entries are
    ignored rather than raised — a bad env var must not take the serve
    tier down at import time."""
    return {name: (cfg["weight"], cfg["deadline_s"])
            for name, cfg in tenant_table(spec).items()}


def _parse_inline_spec(spec: str) -> "dict[str, tuple]":
    out: "dict[str, tuple]" = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition("=")
        name = name.strip()
        if not name:
            continue
        w, _, d = w.partition(":")
        try:
            weight = max(int(w), 1) if w.strip() else 1
        except ValueError:
            weight = 1
        try:
            deadline = float(d) if d.strip() else None
            if deadline is not None and deadline <= 0:
                deadline = None
        except ValueError:
            deadline = None
        out[name] = (weight, deadline)
    return out


class Tenant:
    """One tenant's QoS state.  Counters mirror :class:`ServeStats`'s
    lifecycle contract (``submitted`` counts admitted work only; sheds and
    queue-full rejections land in ``rejected``/``shed_*``) so the
    per-tenant subtree reconciles the same way the global section does."""

    __slots__ = ("name", "weight", "slo_p99_ms", "budget", "hist", "lock",
                 "submitted", "completed", "rejected", "failed",
                 "shed_low", "shed_normal", "queue_wait_seconds",
                 "exec_seconds", "rows", "stream_batches",
                 "cache_fraction", "deadline_s", "traces_retained")

    def __init__(self, name: str, weight: int = 1,
                 slo_p99_ms: "float | None" = None,
                 cache_fraction: "float | None" = None,
                 deadline_s: "float | None" = None):
        self.name = str(name)
        self.weight = max(int(weight), 1)
        self.slo_p99_ms = None if slo_p99_ms is None else float(slo_p99_ms)
        # default request deadline: requests that name no deadline_s of
        # their own inherit this (an explicit request value always wins)
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        # this tenant's slice of the service budget; max_bytes is set by
        # TenantRegistry._rebalance (0 until the service sizes it)
        self.budget = InFlightBudget(0)
        self.hist = LatencyHistogram()
        self.lock = threading.Lock()
        self.submitted = 0
        self.completed = 0
        self.rejected = 0
        self.failed = 0
        self.shed_low = 0
        self.shed_normal = 0
        self.queue_wait_seconds = 0.0
        self.exec_seconds = 0.0
        self.rows = 0
        self.stream_batches = 0
        # requests whose trace the tail sampler kept (the per-tenant half
        # of the exemplar story: how many of MY requests are inspectable)
        self.traces_retained = 0
        self.cache_fraction = cache_fraction

    def as_dict(self) -> dict:
        """This tenant's ``serve.tenants.<name>`` subtree: flows compose by
        addition across registries; ``weight``/``slo_p99_ms``/
        ``budget_bytes`` are gauges (obs merges max them)."""
        with self.lock:
            out = {
                "weight": self.weight,
                "submitted": self.submitted,
                "completed": self.completed,
                "rejected": self.rejected,
                "failed": self.failed,
                # same nested shape as the serve section's own sheds
                # counter, so readers (CLI table, doctor) share one path
                "sheds": {"low": self.shed_low, "normal": self.shed_normal},
                "queue_wait_seconds": round(self.queue_wait_seconds, 6),
                "exec_seconds": round(self.exec_seconds, 6),
                "rows": self.rows,
                "stream_batches": self.stream_batches,
                "traces_retained": self.traces_retained,
                "budget_bytes": self.budget.max_bytes,
            }
            if self.slo_p99_ms is not None:
                out["slo_p99_ms"] = self.slo_p99_ms
            if self.deadline_s is not None:
                out["deadline_s"] = self.deadline_s
            return out


class TenantRegistry:
    """The tenant table + budget-slice arithmetic.

    ``max_memory`` is the service's whole in-flight budget; each tenant's
    slice is ``max_memory * weight / total_weight`` (or an explicit
    ``budget_fraction``), recomputed whenever the table changes — so the
    slices always partition the same bytes the global budget bounds, and
    a tenant's worst case is its fair share, not the whole pool."""

    def __init__(self, max_memory: int = 0, spec: "str | None" = None):
        self.max_memory = int(max_memory)
        self._lock = threading.Lock()
        self._tenants: "dict[str, Tenant]" = {}
        if spec is None:
            spec = os.environ.get("TPQ_SERVE_TENANTS")
        for name, cfg in tenant_table(spec).items():
            self._tenants[name] = Tenant(name, weight=cfg["weight"],
                                         deadline_s=cfg["deadline_s"],
                                         slo_p99_ms=cfg["slo_p99_ms"])
        if DEFAULT_TENANT not in self._tenants:
            self._tenants[DEFAULT_TENANT] = Tenant(DEFAULT_TENANT)
        self._rebalance_locked()

    @classmethod
    def from_file(cls, path: str, max_memory: int = 0) -> "TenantRegistry":
        """The fleet form: every process loads ONE shared tenants.json
        (equivalent to ``spec="@"+path``; malformed degrades to the
        default table, never raises)."""
        return cls(max_memory=max_memory, spec=f"@{path}")

    def _rebalance_locked(self) -> None:
        total = sum(t.weight for t in self._tenants.values()) or 1
        for t in self._tenants.values():
            t.budget.max_bytes = (
                int(self.max_memory * t.weight / total)
                if self.max_memory > 0 else 0)

    def set_max_memory(self, max_memory: int) -> None:
        with self._lock:
            self.max_memory = int(max_memory)
            self._rebalance_locked()

    def register(self, name: str, weight: int = 1,
                 slo_p99_ms: "float | None" = None,
                 cache_fraction: "float | None" = None,
                 deadline_s: "float | None" = None) -> Tenant:
        """Add or reconfigure a tenant; slices rebalance immediately."""
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                t = self._tenants[name] = Tenant(
                    name, weight=weight, slo_p99_ms=slo_p99_ms,
                    cache_fraction=cache_fraction, deadline_s=deadline_s)
            else:
                t.weight = max(int(weight), 1)
                if slo_p99_ms is not None:
                    t.slo_p99_ms = float(slo_p99_ms)
                if cache_fraction is not None:
                    t.cache_fraction = float(cache_fraction)
                if deadline_s is not None:
                    t.deadline_s = float(deadline_s)
            self._rebalance_locked()
            return t

    def get(self, name: "str | None") -> Tenant:
        """Resolve (auto-registering at weight 1) — an unknown tenant is a
        new light user, not an error."""
        name = DEFAULT_TENANT if not name else str(name)
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                t = self._tenants[name] = Tenant(name)
                self._rebalance_locked()
            return t

    def tenants(self) -> "dict[str, Tenant]":
        with self._lock:
            return dict(self._tenants)

    def as_dict(self) -> dict:
        return {name: t.as_dict() for name, t in self.tenants().items()}


class _Empty:
    """Internal marker: no item currently dequeueable (distinct from the
    ``None`` shutdown sentinel, which IS a legal return of ``get``)."""


_EMPTY = _Empty()


class FairScheduler:
    """Bounded multi-tenant admission queue with deficit-round-robin
    dequeue (``fair=True``) or global FIFO (``fair=False``).

    DRR with unit item cost: the cursor visits tenant queues cyclically;
    arriving at a tenant whose deficit is spent refills it by the
    tenant's weight, then serves one item per dequeue while deficit
    remains, advancing only when the quantum is spent or the queue
    empties (an empty queue forfeits its deficit — credit never
    accumulates while idle, the classic DRR anti-burst rule)."""

    def __init__(self, maxsize: int, fair: bool = True):
        self.maxsize = int(maxsize)
        self.fair = bool(fair)
        self._cv = threading.Condition()
        self._queues: "OrderedDict[str, deque]" = OrderedDict()
        self._weights: "dict[str, int]" = {}
        self._deficit: "dict[str, float]" = {}
        self._order: "list[str]" = []
        self._cursor = 0
        self._size = 0
        self._seq = 0
        self._sentinels = 0

    def qsize(self) -> int:
        with self._cv:
            return self._size

    def tenant_depths(self) -> "dict[str, int]":
        with self._cv:
            return {t: len(q) for t, q in self._queues.items() if q}

    def put_nowait(self, tenant: str, weight: int, item) -> None:
        """Enqueue under the GLOBAL bound; ``queue.Full`` when it's hit —
        the fast-reject contract is unchanged, fairness only reorders
        what was admitted."""
        with self._cv:
            if self._size >= self.maxsize:
                raise queue.Full
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
                self._order.append(tenant)
                self._deficit[tenant] = 0.0
            self._weights[tenant] = max(int(weight), 1)
            self._seq += 1
            q.append((self._seq, item))
            self._size += 1
            self._cv.notify()

    def requeue(self, tenant: str, weight: int, item) -> None:
        """Re-enqueue ALREADY-ADMITTED work (a streaming session yielding
        its worker slot between batches).  Exempt from the ``maxsize``
        bound — the item was admitted once and must never bounce on
        re-entry — but it takes a fresh arrival sequence, so DRR charges
        the tenant's deficit again per leg (batch-granular fairness)."""
        with self._cv:
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
                self._order.append(tenant)
                self._deficit[tenant] = 0.0
            self._weights[tenant] = max(int(weight), 1)
            self._seq += 1
            q.append((self._seq, item))
            self._size += 1
            self._cv.notify()

    def has_other_waiters(self, tenant: str) -> bool:
        """True when any OTHER tenant has queued work — the stream-yield
        trigger (a lone tenant's stream keeps its slot; handing it back
        would only add requeue latency)."""
        with self._cv:
            return any(q for t, q in self._queues.items() if t != tenant)

    def put_sentinel(self) -> None:
        """Queue one worker-shutdown sentinel (``get`` returns ``None``).
        Sentinels outrank queued work — close() drains the queues first,
        so by the time sentinels land there is nothing left to starve."""
        with self._cv:
            self._sentinels += 1
            self._cv.notify()

    def drain(self) -> list:
        """Remove and return every queued item (close()'s reject sweep)."""
        with self._cv:
            items = []
            for q in self._queues.values():
                items.extend(it for _seq, it in q)
                q.clear()
            self._size = 0
            for t in self._deficit:
                self._deficit[t] = 0.0
            return items

    def get(self):
        """Block for the next item (or ``None`` for a shutdown sentinel)."""
        with self._cv:
            while True:
                got = self._pop_locked()
                if not isinstance(got, _Empty):
                    return got
                self._cv.wait()

    def _pop_locked(self):
        if self._sentinels:
            self._sentinels -= 1
            return None
        if not self._size:
            return _EMPTY
        if not self.fair:
            # global FIFO: strictly by arrival sequence across all tenants
            best = min((t for t, q in self._queues.items() if q),
                       key=lambda t: self._queues[t][0][0])
            _seq, item = self._queues[best].popleft()
            self._size -= 1
            return item
        n = len(self._order)
        for _ in range(2 * n):
            t = self._order[self._cursor % n]
            q = self._queues[t]
            if not q:
                self._deficit[t] = 0.0  # idle forfeits its credit
                self._cursor += 1
                continue
            if self._deficit[t] < 1.0:
                self._deficit[t] += self._weights.get(t, 1)
            if self._deficit[t] >= 1.0:
                self._deficit[t] -= 1.0
                _seq, item = q.popleft()
                self._size -= 1
                if self._deficit[t] < 1.0 or not q:
                    self._cursor += 1  # quantum spent (or queue drained)
                return item
            self._cursor += 1
        return _EMPTY  # unreachable with weights >= 1; safe fallback
