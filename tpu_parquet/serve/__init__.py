"""tpu_parquet.serve — the high-QPS concurrent scan service.

Many concurrent callers submit scan requests (file set + projection +
predicate) to one :class:`ScanService`; requests execute over SHARED state —
a bounded read-through :class:`PlanCache` of parsed footers, ScanPlan IR
objects (:mod:`tpu_parquet.scanplan`), and decoded dictionary pages — behind
admission control (bounded queue + :class:`~tpu_parquet.alloc
.InFlightBudget`; a full queue fast-rejects with
:class:`~tpu_parquet.errors.OverloadError`), with per-request p50/p95
latency SLOs in the registry ``serve`` section.

See README "Serving concurrent scans"; ``pq_tool serve-stats`` prints a
run's SLO table, and ``pq_tool doctor`` reads ``admission-bound`` when
queue-wait dominates.
"""

from .cache import BoundDictCache, CacheStats, PlanCache
from .service import (PRIORITY_HIGH, PRIORITY_LOW, PRIORITY_NORMAL,
                      ScanRequest, ScanService, ScanTicket, ServeStats)

__all__ = [
    "BoundDictCache", "CacheStats", "PlanCache",
    "PRIORITY_HIGH", "PRIORITY_LOW", "PRIORITY_NORMAL",
    "ScanRequest", "ScanService", "ScanTicket", "ServeStats",
]
