"""tpu_parquet.serve — the high-QPS concurrent scan service.

Many concurrent callers submit scan requests (file set + projection +
predicate) to one :class:`ScanService`; requests execute over SHARED state —
a bounded read-through :class:`PlanCache` of parsed footers, ScanPlan IR
objects (:mod:`tpu_parquet.scanplan`), and — above it — a tiered
:class:`ResultCache` of decoded column-chunk results and dictionary pages
(host RAM + device HBM; ``TPQ_RESULT_CACHE_MB``/``TPQ_RESULT_CACHE_HBM_MB``)
so a repeated hot scan skips the IO→decompress→decode pipeline entirely —
behind admission control (bounded queue + :class:`~tpu_parquet.alloc
.InFlightBudget`; a full queue fast-rejects with
:class:`~tpu_parquet.errors.OverloadError`), with per-request p50/p95
latency SLOs in the registry ``serve`` section.

See README "Serving concurrent scans" / "Serving hot scans from cache";
``pq_tool serve-stats`` prints a run's SLO table and cache hit rates, and
``pq_tool doctor`` reads ``admission-bound`` when queue-wait dominates or
``cache-thrash`` when the result tier churns.
"""

from .cache import BoundDictCache, CacheStats, PlanCache
from .result_cache import (BoundResultCache, ResultCache, ResultTierStats,
                           decode_signature)
from .service import (PRIORITY_HIGH, PRIORITY_LOW, PRIORITY_NORMAL,
                      ScanRequest, ScanService, ScanTicket, ServeStats)
from .stream import (StreamingScan, check_cursor_compatible, pack_cursor,
                     request_digest, unpack_cursor)
from .tenancy import (DEFAULT_TENANT, FairScheduler, Tenant, TenantRegistry,
                      parse_tenant_spec)

__all__ = [
    "BoundDictCache", "BoundResultCache", "CacheStats", "DEFAULT_TENANT",
    "FairScheduler", "PlanCache",
    "PRIORITY_HIGH", "PRIORITY_LOW", "PRIORITY_NORMAL",
    "ResultCache", "ResultTierStats", "ScanRequest", "ScanService",
    "ScanTicket", "ServeStats", "StreamingScan", "Tenant", "TenantRegistry",
    "check_cursor_compatible", "decode_signature", "pack_cursor",
    "parse_tenant_spec", "request_digest", "unpack_cursor",
]
