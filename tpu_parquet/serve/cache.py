"""PlanCache: the shared read-through state a high-QPS scan service runs on.

Every one-shot open pays three metadata costs before the first data byte:
the footer thrift parse, the ScanPlan construction (group pruning + the
footer walk into chunk byte ranges), and — for dictionary-encoded columns —
the dictionary-page decompress + decode, per chunk, per scan.  Under
concurrent traffic over a bounded working set those costs repeat millions of
times for identical inputs.  This module holds all three behind ONE bounded
LRU keyed by file *generation*:

- **footers** (parsed ``FileMetaData`` + a ``Schema``) keyed by
  ``(path, size, mtime_ns)`` for local files, or ``(identity_token, size)``
  for :class:`~tpu_parquet.iostore.ByteStore`-backed objects — the
  read-through footer cache ROADMAP item 4 owed for re-opened
  ``GenericRangeStore`` objects.  A changed file changes its key, so stale
  entries can never be served (and the previous generation is dropped
  eagerly — ``invalidations`` counts them);
- **ScanPlans** (:mod:`tpu_parquet.scanplan`) keyed by
  ``(file key, projection, filter fingerprint)`` — replayed, not rebuilt,
  so the route memo and pruning memo accumulate across requests;
- **decoded dictionaries** keyed by ``(file key, row group, column,
  decode kind)`` — shared read-only with every decoder
  (:class:`BoundDictCache` is the per-file adapter the readers duck-call).

Bounded: total cached bytes are capped (``TPQ_PLAN_CACHE_MB``, default 256)
with LRU eviction; ``hits``/``misses``/``evictions`` counters per kind ride
the registry ``serve`` section and the flight dumps.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict

from ..footer import read_file_metadata
from ..iostore import ByteStore
from ..obs import current_request_trace, env_int

__all__ = ["PlanCache", "BoundDictCache", "CacheStats"]

_KINDS = ("footer", "plan", "dict")


class CacheStats:
    """Per-kind hit/miss/eviction counters (thread-safe via the owning
    cache's lock; this object only aggregates)."""

    __slots__ = ("hits", "misses", "evictions", "invalidations")

    def __init__(self):
        self.hits = {k: 0 for k in _KINDS}
        self.misses = {k: 0 for k in _KINDS}
        self.evictions = 0
        self.invalidations = 0

    def as_dict(self) -> dict:
        return {
            **{f"{k}_hits": self.hits[k] for k in _KINDS},
            **{f"{k}_misses": self.misses[k] for k in _KINDS},
            "evictions": self.evictions,
            "invalidations": self.invalidations,
        }


class PlanCache:
    """Bounded read-through cache over footers, ScanPlans, and decoded
    dictionaries.  Thread-safe; one instance is shared by every worker of a
    :class:`~tpu_parquet.serve.ScanService` (or passed to ``scan_files``
    via ``plan_cache=``)."""

    def __init__(self, max_bytes: "int | None" = None, results=None,
                 result_cache_mb: "int | None" = None,
                 result_cache_hbm_mb: "int | None" = None):
        from .result_cache import ResultCache

        if max_bytes is None:
            max_bytes = env_int("TPQ_PLAN_CACHE_MB", 256, lo=1) << 20
        self.max_bytes = int(max_bytes)
        # the tiered decoded-result cache (result_cache.py) this plan cache
        # feeds: decoded DICTIONARIES live there (one LRU, one byte budget
        # — the PR 10 dict seam folded), and — when sized (the explicit
        # MB args, else TPQ_RESULT_CACHE_MB/TPQ_RESULT_CACHE_HBM_MB) —
        # decoded column-chunk results too.  With the result tier off the
        # dictionary store stays bounded by THIS cache's budget.
        self.results = (results if results is not None else ResultCache(
            max_bytes=(None if result_cache_mb is None
                       else int(result_cache_mb) << 20),
            hbm_bytes=(None if result_cache_hbm_mb is None
                       else int(result_cache_hbm_mb) << 20),
            dict_fallback_bytes=self.max_bytes))
        self.stats = CacheStats()
        self._lock = threading.Lock()
        # full key -> (value, nbytes); insertion order = recency (LRU)
        self._entries: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._bytes = 0
        # file identity -> current generation key: a re-opened file whose
        # generation moved drops the stale entries eagerly instead of
        # letting them age out of the LRU
        self._gen: dict = {}
        # single-flight build locks: N concurrent first-touches of one key
        # build ONCE (the "footer parsed exactly once per file" acceptance
        # is a guarantee, not a race outcome); late arrivals count as hits
        self._building: dict = {}

    # -- identity --------------------------------------------------------------

    @staticmethod
    def file_key(source, store: "ByteStore | None" = None):
        """The file-generation cache key, or None when the source has no
        stable identity (an anonymous stream: never cached, never stale).

        Local paths key by ``(abspath, size, mtime_ns)``; stores by their
        ``identity_token`` + ``size()`` (the satellite contract: a changed
        object — new token or new size — invalidates cleanly)."""
        if store is not None and isinstance(store, ByteStore):
            tok = store.identity_token
            if tok is None:
                return None
            return ("store", tok, int(store.size()))
        if isinstance(source, (str, os.PathLike)):
            path = os.path.abspath(os.fspath(source))
            try:
                st = os.stat(path)
            except OSError:
                return None
            return ("file", path, int(st.st_size), int(st.st_mtime_ns))
        return None

    # -- the one LRU -----------------------------------------------------------

    def _get(self, kind: str, key: tuple):
        with self._lock:
            full = (kind, *key)
            hit = self._entries.get(full)
            if hit is not None:
                self._entries.move_to_end(full)
                self.stats.hits[kind] += 1
                return hit[0]
            self.stats.misses[kind] += 1
            return None

    def _read_through(self, kind: str, key: tuple, build):
        """Get-or-build with single-flight semantics: exactly one builder
        per key runs (one miss counted); concurrent callers wait on the
        build lock and count as hits.  ``build()`` returns
        ``(value, nbytes)``."""
        tr = current_request_trace()
        if tr is None:
            return self._read_through_inner(kind, key, build)
        t0 = time.perf_counter()
        misses0 = self.stats.misses[kind]
        try:
            return self._read_through_inner(kind, key, build)
        finally:
            # best-effort hit attribution: under concurrent traffic a
            # neighbor's miss can tick between our two reads, but a probe
            # span is evidence, not accounting
            tr.add_timed(f"cache_{kind}", t0, time.perf_counter(),
                         hit=self.stats.misses[kind] == misses0)

    def _read_through_inner(self, kind: str, key: tuple, build):
        full = (kind, *key)
        with self._lock:
            hit = self._entries.get(full)
            if hit is not None:
                self._entries.move_to_end(full)
                self.stats.hits[kind] += 1
                return hit[0]
            lock = self._building.get(full)
            if lock is None:
                lock = self._building[full] = threading.Lock()
        with lock:
            with self._lock:
                hit = self._entries.get(full)
                if hit is not None:
                    self._entries.move_to_end(full)
                    self.stats.hits[kind] += 1
                    return hit[0]
                self.stats.misses[kind] += 1
            try:
                value, nbytes = build()
                # publish BEFORE dropping the build lock's registration: a
                # thread arriving after the pop must find the entry (pop
                # first and it would rebuild — a second counted miss and a
                # second plan object whose memos no longer accumulate)
                self._put(kind, key, value, nbytes)
            finally:
                with self._lock:
                    self._building.pop(full, None)
            return value

    def _drop_stale_locked(self, ident: tuple, keep) -> int:
        """Drop every cached entry whose file key shares ``ident`` but is
        not ``keep`` (the current generation; None drops ALL of the
        identity's entries).  One copy of the invalidation bookkeeping —
        shared by the read path (:meth:`_put` observing a moved footer)
        and the write path (:meth:`note_mutation`).  Caller holds the
        lock; returns the number of entries dropped."""
        stale = [f for f in self._entries
                 if isinstance(f[1], tuple)
                 and f[1][:2] == ident and f[1] != keep]
        for f in stale:
            _v, n = self._entries.pop(f)
            self._bytes -= n
            self.stats.invalidations += 1
        return len(stale)

    def _put(self, kind: str, key: tuple, value, nbytes: int) -> None:
        with self._lock:
            full = (kind, *key)
            old = self._entries.pop(full, None)
            if old is not None:
                self._bytes -= old[1]
            nbytes = max(int(nbytes), 1)
            self._entries[full] = (value, nbytes)
            self._bytes += nbytes
            # generation bookkeeping: a new generation of the same file
            # drops the PREVIOUS generation's entries in full (footer/plan/
            # dict alike) — they can never be served again, so aging them
            # out of the LRU is pure waste.  A file key is ("file", path,
            # size, mtime_ns) or ("store", token, size); identity = kind +
            # name, generation = the full tuple.
            fk = key[0]
            moved = False
            if isinstance(fk, tuple) and len(fk) >= 2:
                ident = fk[:2]
                prev = self._gen.get(ident)
                if prev is not None and prev != fk:
                    moved = True
                    self._drop_stale_locked(ident, fk)
                self._gen[ident] = fk
            # ONE byte budget: when the result tier is unsized, the
            # dictionary store rides THIS cache's budget — its resident
            # bytes displace footer/plan entries here (a 1/16 slice is
            # always reserved for footers/plans so a dictionary flood
            # cannot evict every footer)
            limit = self.max_bytes
            if self.results.dict_fallback_active:
                limit = max(self.max_bytes - self.results.host_held(),
                            self.max_bytes // 16, 1)
            while self._bytes > limit and len(self._entries) > 1:
                _f, (_v, n) = self._entries.popitem(last=False)
                self._bytes -= n
                self.stats.evictions += 1
        if moved:
            # decoded results invalidate at the same moment plans do — the
            # mutated file's cached chunks/dictionaries can never be
            # served, and the result cache's `invalidations` counters must
            # account them NOW, not whenever a later decode happens by
            self.results.note_generation(fk)

    # -- footers ---------------------------------------------------------------

    def footer(self, source, store: "ByteStore | None" = None):
        """Read-through footer: ``(FileMetaData, Schema)`` for a path or a
        ByteStore-backed object.  Un-keyable sources load fresh every time
        (counted as misses) — correct, just uncached."""
        from ..schema.core import Schema

        def build():
            if store is not None and isinstance(store, ByteStore):
                meta = read_file_metadata(_StoreFile(store),
                                          validate_head_magic=False)
                nbytes = _footer_len(store=store)
            else:
                meta = read_file_metadata(source)
                nbytes = _footer_len(path=source)
            return (meta, Schema.from_file_metadata(meta)), nbytes + 4096

        key = self.file_key(source, store)
        if key is None:
            with self._lock:
                self.stats.misses["footer"] += 1
            return build()[0]
        return self._read_through("footer", (key,), build)

    # -- plans -----------------------------------------------------------------

    def plan(self, key, columns, row_filter, meta=None, schema=None,
             source=None, store=None):
        """Read-through ScanPlan for ``(file key, projection, filter)``.

        ``meta``/``schema`` may be passed when the caller already holds the
        footer; otherwise they read through :meth:`footer`.  Returns the
        SHARED plan object — its route/pruning memos accumulate across every
        consumer, which is the point."""
        from ..scanplan import build_scan_plan, predicate_fingerprint

        fp = predicate_fingerprint(row_filter)
        cols_sig = _columns_sig(columns)

        def build():
            m, s = ((meta, schema) if meta is not None and schema is not None
                    else self.footer(source, store))
            sel = _selected_schema(s, columns)
            plan = build_scan_plan(m, sel, file_key=key,
                                   row_filter=row_filter, filter_fp=fp)
            return plan, plan.nbytes()

        cacheable = key is not None and (row_filter is None or fp is not None)
        if not cacheable:
            with self._lock:
                self.stats.misses["plan"] += 1
            return build()[0]
        return self._read_through("plan", (key, cols_sig, fp), build)

    # -- decoded dictionaries --------------------------------------------------
    # Folded into the tiered ResultCache (one LRU, one byte budget with the
    # decoded chunk results — not a parallel dictionary budget); these
    # delegates keep the PR 10 seam and its counters stable.

    def dict_get(self, key, rg, column, kind):
        if key is None:
            return None
        from .result_cache import ResultCache

        hit = self.results.get(ResultCache.dict_key(key, rg, column, kind))
        with self._lock:
            if hit is not None:
                self.stats.hits["dict"] += 1
            else:
                self.stats.misses["dict"] += 1
        return hit

    def dict_put(self, key, rg, column, kind, value, nbytes) -> None:
        if key is None:
            return
        from .result_cache import ResultCache

        self.results.put(ResultCache.dict_key(key, rg, column, kind),
                         value, nbytes, "host")

    # -- writer integration ----------------------------------------------------

    def note_mutation(self, source, store: "ByteStore | None" = None) -> int:
        """Eagerly invalidate a file the write side just REPLACED or
        removed (the sharded writer's atomic publish and the compaction
        service call this the moment their ``os.replace`` lands).

        Without it, stale plans/results die only when the next footer
        open happens to observe the new generation; with it, the
        invalidation is synchronous with the mutation — the counters a
        mutation-mid-sweep test can assert exactly.  Computes the path's
        NEW generation key and drops every entry of previous generations
        across footers/plans/dictionaries, then notifies the decoded-
        result tier (:meth:`ResultCache.note_generation`).  A file that
        no longer exists (compaction removed it) drops by identity; its
        decoded results are unreachable afterwards (the key can never be
        rebuilt) and age out of the LRU.  Returns the number of
        plan-cache entries dropped."""
        fk = self.file_key(source, store)
        with self._lock:
            if fk is None:
                if not isinstance(source, (str, os.PathLike)):
                    return 0
                ident = ("file", os.path.abspath(os.fspath(source)))
                dropped = self._drop_stale_locked(ident, None)
                self._gen.pop(ident, None)
                return dropped
            ident = fk[:2]
            dropped = 0
            if self._gen.get(ident) != fk:
                dropped = self._drop_stale_locked(ident, fk)
                self._gen[ident] = fk
        self.results.note_generation(fk)
        return dropped

    # -- reader integration ----------------------------------------------------

    def bind_results(self, key, plan, row_filter=None, device: bool = False,
                     validate_crc=None, tenant: "str | None" = None):
        """The ONE bind gate for the decoded-result tier (shared by
        :meth:`reader_kwargs` and ``ScanService``): a filtered DEVICE
        scan whose predicate has no stable fingerprint gets no result
        cache — two unfingerprintable predicates must never share
        page-pruned device output.  ``tenant`` attributes inserts to that
        tenant's cache byte share (ISSUE 17).  Returns a
        :class:`~tpu_parquet.serve.BoundResultCache` or None."""
        if device and row_filter is not None and plan.filter_fp is None:
            return None
        return self.results.bind(key, device=device,
                                 validate_crc=validate_crc,
                                 filter_fp=plan.filter_fp, tenant=tenant)

    def reader_kwargs(self, source, columns=None, row_filter=None,
                      store: "ByteStore | None" = None, device: bool = False,
                      validate_crc=None) -> dict:
        """The ``metadata=``/``plan=``/``dict_cache=`` (and, when the
        result tier is sized, ``result_cache=``) kwargs that make a
        ``FileReader``/``DeviceFileReader`` (or ``scan_files``) run over
        this cache's shared state.  ``device``/``validate_crc`` pin the
        decode signature of the result tier (see :meth:`bind_results`)
        and MUST match the consuming reader: the default (host shape,
        env-resolved CRC) fits a bare ``FileReader``; pass
        ``device=True`` for ``DeviceFileReader``/``scan_files``.  The
        readers verify the signature at adoption and drop a mismatched
        adapter rather than serve the wrong decode shape — a mismatch
        costs the caching, never correctness."""
        key = self.file_key(source, store)
        meta, schema = self.footer(source, store)
        plan = self.plan(key, columns, row_filter, meta=meta, schema=schema)
        kw = {"metadata": meta, "plan": plan,
              "dict_cache": BoundDictCache(self, key)}
        rc = self.bind_results(key, plan, row_filter=row_filter,
                               device=device, validate_crc=validate_crc)
        if rc is not None:
            kw["result_cache"] = rc
        return kw

    # -- reporting -------------------------------------------------------------

    def counters(self) -> dict:
        with self._lock:
            return {
                **self.stats.as_dict(),
                "held_bytes": self._bytes,
                "capacity_bytes": self.max_bytes,
                "entries": len(self._entries),
            }

    # flight-source sample (obs.register_flight_source duck type)
    sample = counters


class BoundDictCache:
    """A :class:`PlanCache` bound to one file generation — the adapter the
    chunk decoders duck-call (``get(rg, column, kind)`` /
    ``put(rg, column, kind, value, nbytes)``).  ``kind`` separates the two
    decode shapes ("host": plain-decoded arrays, "dev": the device
    assembler's value-table entry)."""

    __slots__ = ("cache", "key")

    def __init__(self, cache: PlanCache, key):
        self.cache = cache
        self.key = key

    def get(self, rg, column, kind):
        return self.cache.dict_get(self.key, rg, column, kind)

    def put(self, rg, column, kind, value, nbytes) -> None:
        self.cache.dict_put(self.key, rg, column, kind, value, nbytes)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

class _StoreFile:
    """Minimal seek/read file view over a ByteStore (whence-aware, which
    the SharedReader pread view deliberately is not) — enough for
    :func:`~tpu_parquet.footer.read_file_metadata`."""

    __slots__ = ("_s", "_pos")

    def __init__(self, store: ByteStore):
        self._s = store
        self._pos = 0

    def seek(self, pos: int, whence: int = 0) -> int:
        if whence == os.SEEK_END:
            self._pos = self._s.size() + pos
        elif whence == os.SEEK_CUR:
            self._pos += pos
        else:
            self._pos = pos
        return self._pos

    def read(self, size: int = -1) -> bytes:
        if size < 0:
            size = max(self._s.size() - self._pos, 0)
        b = self._s.read_range(self._pos, size)
        self._pos += len(b)
        return b


def _footer_len(path=None, store: "ByteStore | None" = None) -> int:
    """The footer's thrift length (cache accounting): read from the 8-byte
    tail; 0 on any failure (accounting only, never correctness)."""
    import struct

    try:
        if store is not None:
            size = store.size()
            tail = store.read_range(size - 8, 8)
        else:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(size - 8)
                tail = f.read(8)
        return struct.unpack("<I", tail[:4])[0]
    except Exception:  # noqa: BLE001 — accounting only
        return 0


def _columns_sig(columns) -> "tuple | None":
    if columns is None:
        return None
    out = []
    for c in columns:
        out.append(c if isinstance(c, str) else ".".join(c))
    return tuple(sorted(out))


def _selected_schema(schema, columns):
    """A fresh Schema with ``columns`` applied (the shared cached Schema is
    never mutated — selection is per-consumer state)."""
    if columns is None:
        return schema
    import copy

    from ..scanplan import apply_selection

    sel = copy.deepcopy(schema)
    apply_selection(sel, columns)
    return sel
