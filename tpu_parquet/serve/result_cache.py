"""ResultCache: the tiered decoded-result cache above PlanCache.

PlanCache (ISSUE 10) made the *planning* side of a repeated scan nearly
free, but every plan-cache hit still pays the dominant cost: the full
IO→decompress→decode pipeline.  For the serve tier's workload — many users
re-scanning a hot working set — the decoded values themselves are the
layer to cache (the reference's L2/L5 split in PAPER.md §1: decoded values
are a layer).  This module holds them behind ONE two-tier bounded LRU:

- **host tier** (``TPQ_RESULT_CACHE_MB``): decoded column-chunk results
  (host ``ColumnData``) and decoded dictionary pages — the PR 10
  ``dict_cache`` seam is SUBSUMED here: one LRU, one byte budget, not two
  (:class:`~tpu_parquet.serve.PlanCache` delegates ``dict_get``/
  ``dict_put`` into this cache);
- **device tier** (``TPQ_RESULT_CACHE_HBM_MB``): decoded
  ``DeviceColumnData`` resident in HBM.  Residency is registered on the
  cache's own :class:`~tpu_parquet.alloc.AllocTracker` device ledger
  (``register_device``/``release_device``) so flight dumps and
  ``device_snapshot()`` show the cache's HBM footprint, and eviction under
  device-memory pressure happens WITHIN the device tier — host entries are
  never sacrificed to relieve HBM, and vice versa.

Keys are ``(file generation key, row group, column, decode signature)``,
reusing :meth:`PlanCache.file_key` generation semantics: a mutated file
changes its key, the stale generation is dropped eagerly (``invalidations``
counted exactly), and stale decoded bytes can never be served.  The decode
signature (:func:`decode_signature`) covers the decode SHAPE — host vs
device arrays, the CRC tier, the filter fingerprint (page pruning shapes
device output), and the ship/fuse route-relevant knobs — so two requests
share an entry exactly when their decode is bit-identical by contract.
(The projection dtype is a function of the file generation's schema, so
the generation key already pins it.)

Builds are SINGLE-FLIGHT on the host chunk seam (``get_or_build``): N
concurrent first-touches of one chunk decode it once; late arrivals wait
on the build and adopt the published entry (``single_flight_waits``
counts them).  The DEVICE seam publishes at finalize instead (the one
point that proves the deferred validity checks passed), so concurrent
cold device scans of one file may each decode — the group probe dedupes
all traffic once the first finalize publishes.  Cached values are shared
READ-ONLY — the same contract the decoded-dictionary seam already
carries.

The chunk tier is OFF by default (``TPQ_RESULT_CACHE_MB`` unset/0): a
plain reader pays nothing.  The serve tier (or ``scan_files(plan_cache=)``
with a sized cache) turns it on; dictionaries are always cached, bounded
by the plan cache's budget when no result budget is set.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

from ..alloc import AllocTracker
from ..obs import current_request_trace, env_int, register_flight_source

__all__ = ["BoundResultCache", "ResultCache", "ResultTierStats",
           "decode_signature", "column_nbytes", "device_column_nbytes"]

TIERS = ("host", "device")

# per-tier cap on the eviction-attribution map (doctor's `cache-thrash`
# verdict names the top-evicting file; an unbounded map would let a
# pathological key stream grow it without limit)
_EVICT_FILES_CAP = 64


def decode_signature(device: bool, validate_crc=None, filter_fp=None):
    """The decode-shape half of a result key.

    Two lookups may share a cached entry only when their decode is
    bit-identical by contract: same output shape (host ``ColumnData`` vs
    device arrays), same CRC tier (a ``validate_crc=True`` request must
    never adopt an unvalidated decode — the dict-cache precedent), and —
    for the device shape — the same filter fingerprint (page pruning drops
    whole-page row runs from device output) and the same route-relevant
    knobs (``TPQ_FORCE_ROUTE``/``TPQ_FUSE``; routes are bit-identical by
    contract, the knobs ride the key as cheap insurance against a
    mid-process knob flip serving a differently-shaped array).
    """
    from ..quarantine import resolve_validate

    crc = "v1" if resolve_validate(validate_crc) else "v0"
    if not device:
        return ("host", crc)
    import os

    from ..ship import fuse_enabled

    return ("dev", crc, filter_fp,
            os.environ.get("TPQ_FORCE_ROUTE") or None,
            bool(fuse_enabled()))


def column_nbytes(cd) -> int:
    """Accounting size of a host ColumnData (values + levels)."""
    from ..column import ByteArrayData

    n = 0
    v = cd.values
    if isinstance(v, ByteArrayData):
        n += int(v.offsets.nbytes) + int(v.heap.nbytes)
    elif v is not None:
        n += int(v.nbytes)
    for attr in ("def_levels", "rep_levels"):
        a = getattr(cd, attr, None)
        if a is not None:
            n += int(a.nbytes)
    return n


def device_column_nbytes(cd) -> int:
    """Accounting size of a DeviceColumnData (every device array it pins,
    dictionary tables of a lazy DeviceDictColumn included)."""
    n = 0
    for attr in ("values", "offsets", "heap", "def_levels", "rep_levels",
                 "indices", "dict_u8", "dict_offsets", "dict_heap"):
        a = getattr(cd, attr, None)
        if a is not None and hasattr(a, "nbytes"):
            n += int(a.nbytes)
    return n


class ResultTierStats:
    """One tier's counters.  All flows except the gauges the owner's
    ``counters()`` computes; mutated only under the owning cache's lock."""

    __slots__ = ("hits", "misses", "evictions", "invalidations", "rejected")

    def __init__(self):
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        self.rejected = 0


class ResultCache:
    """Two-tier bounded LRU of decoded results.  Thread-safe; one instance
    is shared by every consumer of a :class:`~tpu_parquet.serve.PlanCache`
    (which owns one — ``PlanCache().results``).

    ``max_bytes``/``hbm_bytes`` default from ``TPQ_RESULT_CACHE_MB`` /
    ``TPQ_RESULT_CACHE_HBM_MB`` (MB; 0 disables the tier).  When the host
    knob is unset the cache still serves as the decoded-DICTIONARY store
    (the folded PR 10 seam) with ``chunks_enabled`` False — pass
    ``dict_fallback_bytes`` (the plan cache's budget) so dictionaries stay
    bounded by exactly one budget either way.
    """

    def __init__(self, max_bytes: "int | None" = None,
                 hbm_bytes: "int | None" = None,
                 chunks_enabled: "bool | None" = None,
                 dict_fallback_bytes: int = 0):
        if max_bytes is None:
            max_bytes = env_int("TPQ_RESULT_CACHE_MB", 0, lo=0) << 20
        if hbm_bytes is None:
            hbm_bytes = env_int("TPQ_RESULT_CACHE_HBM_MB", 0, lo=0) << 20
        if chunks_enabled is None:
            chunks_enabled = max_bytes > 0 or hbm_bytes > 0
        self.chunks_enabled = bool(chunks_enabled)
        # per-tier chunk admission: an unset host knob leaves the host tier
        # as the dictionary store alone (bounded by the plan cache's
        # budget), never a silent chunk cache riding the fallback budget
        self._chunk_tier_ok = {"host": max_bytes > 0, "device": hbm_bytes > 0}
        # True when the host tier runs as the dictionary store alone on
        # the PLAN cache's budget — PlanCache then counts these bytes
        # against its own eviction limit (one budget, not a parallel one)
        self.dict_fallback_active = max_bytes <= 0 and dict_fallback_bytes > 0
        if max_bytes <= 0:
            max_bytes = int(dict_fallback_bytes)
        self._caps = {"host": int(max_bytes), "device": int(hbm_bytes)}
        # HBM residency ledger: the device tier's bytes are visible in
        # flight dumps / device_snapshot() like any staged buffer's
        self.tracker = AllocTracker(0)
        self.stats = {t: ResultTierStats() for t in TIERS}
        self.single_flight_waits = 0
        self._lock = threading.Lock()
        # full key -> (value, nbytes, tier, tenant); recency lives in the
        # per-tier index below — ONE combined order would make every
        # eviction an O(total entries) scan for a same-tier victim
        self._entries: "dict[tuple, tuple]" = {}
        # multi-tenant byte shares (ISSUE 17): tenant name -> fraction of
        # each tier's capacity that tenant's entries may hold.  A tenant
        # over its share evicts ITS OWN LRU entries first — one hot
        # tenant's working set cannot flush a neighbor's.  Tenants
        # without a share compete freely under the global bound.
        self._tenant_share: "dict[str, float]" = {}
        self._tenant_bytes = {t: {} for t in TIERS}
        # per-tier LRU index: full key -> None, insertion order = recency
        self._lru = {t: OrderedDict() for t in TIERS}
        self._bytes = {t: 0 for t in TIERS}
        # file identity -> current generation (eager stale-generation drop,
        # same scheme as PlanCache)
        self._gen: dict = {}
        # single-flight build locks
        self._building: dict = {}
        # keys whose built value exceeded its tier cap: bypass the
        # single-flight lock for them — otherwise N concurrent scans of an
        # uncachable chunk would decode it N times SEQUENTIALLY behind the
        # per-key build lock (each builder's put rejects, each waiter
        # retries as the next builder).  Bounded; cleared when full.
        self._uncachable: set = set()
        # per-tier {file name: evictions} for doctor's cache-thrash verdict
        self._evict_files = {t: {} for t in TIERS}
        register_flight_source("result_cache", self, "counters")

    # -- keys ------------------------------------------------------------------

    @staticmethod
    def chunk_key(file_key, rg: int, column: str, sig) -> tuple:
        return ("chunk", file_key, int(rg), column, sig)

    @staticmethod
    def dict_key(file_key, rg: int, column: str, kind) -> tuple:
        return ("dict", file_key, int(rg), column, kind)

    def tier_capacity(self, tier: str) -> int:
        return self._caps[tier]

    def host_held(self) -> int:
        """Host-tier resident bytes (PlanCache's shared-budget input)."""
        with self._lock:
            return self._bytes["host"]

    def bind(self, file_key, device: bool = False, validate_crc=None,
             filter_fp=None,
             tenant: "str | None" = None) -> "BoundResultCache | None":
        """The per-(file, decode-shape) adapter the readers duck-call, or
        None when this cache cannot serve chunk results for it (chunk tier
        off, un-keyable source, or the shape's tier has no budget).
        ``tenant`` attributes the adapter's inserts to that tenant's byte
        share; lookups are share-blind (a warm entry serves anyone — the
        share bounds what a tenant may HOLD, not what it may read)."""
        if not self.chunks_enabled or file_key is None:
            return None
        tier = "device" if device else "host"
        if not self._chunk_tier_ok[tier] or self._caps[tier] <= 0:
            return None
        sig = decode_signature(device, validate_crc, filter_fp)
        return BoundResultCache(self, file_key, sig, tenant=tenant)

    def set_tenant_share(self, tenant: str, fraction: "float | None") -> None:
        """Cap ``tenant``'s resident bytes at ``fraction`` of each tier's
        capacity (None removes the cap).  Enforced at insert time — an
        already-resident overage ages out through the tenant-first
        eviction on the tenant's next inserts."""
        with self._lock:
            if fraction is None:
                self._tenant_share.pop(tenant, None)
            else:
                self._tenant_share[tenant] = min(max(float(fraction), 0.0),
                                                 1.0)

    def tenant_bytes(self, tenant: str) -> int:
        """Resident bytes attributed to ``tenant`` across both tiers (the
        ``serve.tenants.<name>.cache_held_bytes`` gauge)."""
        with self._lock:
            return sum(self._tenant_bytes[t].get(tenant, 0) for t in TIERS)

    # -- core LRU --------------------------------------------------------------

    def _remove_locked(self, full) -> "tuple | None":
        """Pop ``full`` from the value map AND its tier's recency index,
        releasing its byte (and device-ledger) accounting."""
        ent = self._entries.pop(full, None)
        if ent is None:
            return None
        _v, n, tier, tenant = ent
        self._lru[tier].pop(full, None)
        self._bytes[tier] -= n
        if tenant is not None:
            tb = self._tenant_bytes[tier]
            left = tb.get(tenant, 0) - n
            if left > 0:
                tb[tenant] = left
            else:
                tb.pop(tenant, None)
        if tier == "device":
            self.tracker.release_device(n)
        return ent

    @staticmethod
    def _file_name(full) -> str:
        fk = full[1]
        if isinstance(fk, tuple) and len(fk) >= 2:
            return str(fk[1])
        return str(fk)

    def _note_evict_locked(self, tier: str, full) -> None:
        files = self._evict_files[tier]
        name = self._file_name(full)
        if name not in files and len(files) >= _EVICT_FILES_CAP:
            return
        files[name] = files.get(name, 0) + 1

    def get(self, full: tuple):
        with self._lock:
            ent = self._entries.get(full)
            if ent is not None:
                self._lru[ent[2]].move_to_end(full)
                self.stats[ent[2]].hits += 1
                return ent[0]
            # a get's tier isn't knowable from an absent key; misses are
            # attributed by the key's kind signature (chunk sig vs dict)
            self.stats[self._tier_of_key(full)].misses += 1
            return None

    @staticmethod
    def _tier_of_key(full) -> str:
        sig = full[4] if len(full) > 4 else None
        return ("device" if isinstance(sig, tuple) and sig
                and sig[0] == "dev" else "host")

    def put(self, full: tuple, value, nbytes: int, tier: str = "host",
            tenant: "str | None" = None) -> bool:
        """Insert (shared read-only).  Returns False when the entry was
        rejected: tier disabled, bigger than the whole tier, or bigger
        than the inserting tenant's byte share — the bounds are hard
        invariants, never exceeded even transiently, so an oversized
        value is simply not cached."""
        nbytes = max(int(nbytes), 1)
        with self._lock:
            cap = self._caps[tier]
            share = (self._tenant_share.get(tenant)
                     if tenant is not None else None)
            tcap = int(cap * share) if share is not None else None
            if cap <= 0 or nbytes > cap or (tcap is not None
                                            and nbytes > tcap):
                self.stats[tier].rejected += 1
                return False
            if not self._invalidate_stale_locked(full):
                # a STALE publisher (a scan still bound to a pre-mutation
                # generation): rejecting it is the only safe move —
                # adopting its generation would wipe the fresh warm set
                # and leave its own stale bytes servable
                self.stats[tier].rejected += 1
                return False
            self._remove_locked(full)
            lru = self._lru[tier]
            # a share-capped tenant over its slice evicts its OWN oldest
            # entries first — its churn stays inside its share and a
            # neighbor's warm set survives the flood
            if tcap is not None:
                tb = self._tenant_bytes[tier]
                while tb.get(tenant, 0) + nbytes > tcap:
                    victim = next((f for f in lru
                                   if self._entries[f][3] == tenant), None)
                    if victim is None:
                        break
                    self._remove_locked(victim)
                    self.stats[tier].evictions += 1
                    self._note_evict_locked(tier, victim)
            # make room within this tier only: device-memory pressure
            # evicts device entries (never host ones), and the byte bound
            # holds at every instant.  O(1) per victim: each tier keeps
            # its own recency index.
            while self._bytes[tier] + nbytes > cap and lru:
                victim = next(iter(lru))
                self._remove_locked(victim)
                self.stats[tier].evictions += 1
                self._note_evict_locked(tier, victim)
            self._entries[full] = (value, nbytes, tier, tenant)
            lru[full] = None
            self._bytes[tier] += nbytes
            if tenant is not None:
                tb = self._tenant_bytes[tier]
                tb[tenant] = tb.get(tenant, 0) + nbytes
            if tier == "device":
                self.tracker.register_device(nbytes)
            return True

    @staticmethod
    def _supersedes(new_fk, cur_fk) -> bool:
        """Does ``new_fk`` supersede the adopted generation ``cur_fk``?

        Local file keys carry ``(kind, path, size, mtime_ns)``: a strictly
        newer mtime supersedes, an OLDER one is a stale publisher (a scan
        that outlived a mutation) and must not; equal mtime with a
        different size is a rewrite on a coarse-mtime filesystem —
        supersede.  Store keys (``(kind, token, size)``) carry no order:
        the incoming generation supersedes, as before — every
        PlanCache-driven flow adopts via :meth:`note_generation` (the
        authoritative footer observation) first anyway."""
        if (new_fk[0] == "file" == cur_fk[0] and len(new_fk) >= 4
                and len(cur_fk) >= 4):
            if new_fk[3] != cur_fk[3]:
                return new_fk[3] > cur_fk[3]
        return True

    def _invalidate_stale_locked(self, full) -> bool:
        """Generation bookkeeping for an insert under key ``full``.

        A new generation of a file drops EVERY entry of its previous
        generation (chunks and dictionaries alike) — they can never be
        served again, so aging them out of the LRU is pure waste, and the
        ``invalidations`` counters account each one exactly.  Returns
        False (and adopts nothing) when the inserting key belongs to a
        generation the adopted one supersedes — a stale publisher (put OR
        straggling footer observation) must never roll the map back and
        wipe the fresh working set."""
        fk = full[1]
        if not (isinstance(fk, tuple) and len(fk) >= 2):
            return True
        ident = fk[:2]
        prev = self._gen.get(ident)
        if prev is None or prev == fk:
            self._gen[ident] = fk
            return True
        if not self._supersedes(fk, prev):
            return False
        stale = [f for f in self._entries
                 if isinstance(f[1], tuple) and f[1][:2] == ident
                 and f[1] != fk]
        for f in stale:
            ent = self._remove_locked(f)
            self.stats[ent[2]].invalidations += 1
        self._gen[ident] = fk
        return True

    def note_generation(self, file_key) -> None:
        """Adopt ``file_key`` as its file's current generation, dropping
        every cached entry of previous generations (PlanCache calls this
        the moment a footer read observes the move, so decoded results
        invalidate in lockstep with plans — never on a later decode's
        schedule).  The :meth:`_supersedes` ordering applies here too: a
        STRAGGLING footer build that completes after the file already
        moved on (its generation is older by mtime) adopts nothing — it
        must not wipe the fresh generation's warm set."""
        if not (isinstance(file_key, tuple) and len(file_key) >= 2):
            return
        with self._lock:
            self._invalidate_stale_locked(("gen", file_key))

    def contains_all(self, keys,
                     count_misses_tier: "str | None" = None) -> bool:
        """Membership probe for the prefetch feed's skip check.  Hits are
        NOT counted here (the authoritative, counted probe happens at
        prepare time); a failed probe counts one miss per key into
        ``count_misses_tier`` when given — on the prefetch path this IS
        the only probe a cold group gets, and an uncounted cold stream
        would make the hit rate read ~100% no matter how hard the tier
        churned (doctor's cache-thrash gate would never trip)."""
        with self._lock:
            ok = all(f in self._entries for f in keys)
            if not ok and count_misses_tier is not None:
                self.stats[count_misses_tier].misses += len(keys)
            return ok

    def get_or_build(self, full: tuple, build, tier: str = "host",
                     tenant: "str | None" = None):
        """Get-or-decode with single-flight semantics: exactly one builder
        per key runs (one counted miss); concurrent callers wait on the
        build and adopt the published entry (counted as hits +
        ``single_flight_waits``).  ``build()`` returns ``(value, nbytes)``;
        a build that raises releases its waiters to retry (a failed decode
        is never published — quarantine containment sees the same error it
        would without the cache)."""
        while True:
            with self._lock:
                ent = self._entries.get(full)
                if ent is not None:
                    self._lru[ent[2]].move_to_end(full)
                    self.stats[ent[2]].hits += 1
                    return ent[0]
                if full in self._uncachable:
                    # known too big for its tier: decode in parallel, no
                    # single-flight serialization for a value that can
                    # never be published anyway
                    self.stats[tier].misses += 1
                    mine, lock = None, None
                else:
                    lock = self._building.get(full)
                    mine = lock is None
                    if mine:
                        lock = self._building[full] = threading.Lock()
                        lock.acquire()
                    else:
                        self.single_flight_waits += 1
            if mine is None:
                return build()[0]
            if mine:
                try:
                    with self._lock:
                        self.stats[tier].misses += 1
                    value, nbytes = build()
                    if not self.put(full, value, nbytes, tier,
                                    tenant=tenant):
                        # every rejection reason is permanent for THIS key
                        # (tier cap, oversized value, stale generation):
                        # release future callers from the single-flight
                        # lock so they decode in parallel, not serially
                        with self._lock:
                            if len(self._uncachable) >= 1024:
                                self._uncachable.clear()
                            self._uncachable.add(full)
                    return value
                finally:
                    with self._lock:
                        self._building.pop(full, None)
                    lock.release()
            else:
                with lock:
                    pass  # builder published (→ hit) or failed (→ retry)

    def lookup_units(self, keys, count_misses: bool = False):
        """All-or-nothing probe of several keys (the full-hit fast paths:
        a served group/request touches recency and counts one hit per
        unit; a failed probe counts nothing unless ``count_misses`` — the
        decode path that follows owns the miss accounting otherwise).
        Returns ``[(value, nbytes), ...]`` in key order, or None."""
        with self._lock:
            out = []
            for f in keys:
                ent = self._entries.get(f)
                if ent is None:
                    if count_misses:
                        t = self._tier_of_key(f)
                        self.stats[t].misses += len(keys)
                    return None
                out.append(ent)
            for f, ent in zip(keys, out):
                self._lru[ent[2]].move_to_end(f)
                self.stats[ent[2]].hits += 1
            return [(e[0], e[1]) for e in out]

    # -- reporting -------------------------------------------------------------

    def counters(self) -> dict:
        """The registry ``cache`` section: per-tier flows + gauges, plus
        the single-flight wait count.  ``held_bytes``/``capacity_bytes``/
        ``entries`` are gauges (obs merges max them); the rest are flows."""
        with self._lock:
            out: dict = {"single_flight_waits": self.single_flight_waits}
            counts = {t: len(self._lru[t]) for t in TIERS}
            knobs = {
                # in dict-fallback mode the host tier's budget IS the
                # plan cache's — doctor's advice must name the knob that
                # actually governs the thrash
                "host": ("TPQ_PLAN_CACHE_MB" if self.dict_fallback_active
                         else "TPQ_RESULT_CACHE_MB"),
                "device": "TPQ_RESULT_CACHE_HBM_MB",
            }
            for t in TIERS:
                st = self.stats[t]
                out[t] = {
                    "hits": st.hits,
                    "misses": st.misses,
                    "evictions": st.evictions,
                    "invalidations": st.invalidations,
                    "rejected": st.rejected,
                    "held_bytes": self._bytes[t],
                    "capacity_bytes": self._caps[t],
                    "entries": counts[t],
                    "budget_knob": knobs[t],
                    # per-file eviction attribution as the raw (bounded)
                    # map: registry merges recurse into it and ADD counts
                    # per file — a precomputed "top file" scalar pair
                    # cannot merge coherently (string LWW + maxed count
                    # would blame the wrong file).  Doctor ranks it.
                    "evict_files": dict(self._evict_files[t]),
                }
            return out

    # flight-source duck type
    sample = counters

    def progress(self) -> dict:
        """Flat monotonic counters for the obs.Sampler track (a live curve
        of hit/miss/eviction flows next to the decode lanes they spare)."""
        with self._lock:
            out = {"single_flight_waits": self.single_flight_waits}
            for t in TIERS:
                st = self.stats[t]
                out[f"{t}_hits"] = st.hits
                out[f"{t}_misses"] = st.misses
                out[f"{t}_evictions"] = st.evictions
            return out


class BoundResultCache:
    """A :class:`ResultCache` bound to one (file generation, decode
    signature) — the adapter the readers duck-call.  Chunk units are
    addressed ``(rg, column)``; values are shared READ-ONLY."""

    __slots__ = ("cache", "key", "sig", "tier", "tenant")

    def __init__(self, cache: ResultCache, key, sig,
                 tenant: "str | None" = None):
        self.cache = cache
        self.key = key
        self.sig = sig
        self.tier = "device" if sig and sig[0] == "dev" else "host"
        self.tenant = tenant

    def _full(self, rg: int, column: str) -> tuple:
        return ResultCache.chunk_key(self.key, rg, column, self.sig)

    def get(self, rg: int, column: str):
        return self.cache.get(self._full(rg, column))

    def put(self, rg: int, column: str, value, nbytes: int) -> bool:
        return self.cache.put(self._full(rg, column), value, nbytes,
                              self.tier, tenant=self.tenant)

    def get_or_build(self, rg: int, column: str, build):
        """``build()`` returns ``(value, nbytes)``; single-flight."""
        return self.cache.get_or_build(self._full(rg, column), build,
                                       self.tier, tenant=self.tenant)

    def has_group(self, rg: int, columns,
                  count_misses: bool = False) -> bool:
        """All-columns membership check for one row group.  Hits are not
        counted (the prepare-time probe owns hit accounting);
        ``count_misses`` charges a failed probe's misses — set it on
        probes that are the group's ONLY cold-path lookup."""
        cols = list(columns)
        return self.cache.contains_all(
            [self._full(rg, c) for c in cols],
            count_misses_tier=self.tier if count_misses else None)

    def lookup_group(self, rg: int, columns) -> "dict | None":
        """All-or-nothing probe of one row group's columns (the device
        reader's group-granular hit path).  Counts hits on success and one
        miss per column on failure (the group will decode that many
        units); returns ``{column: value}`` or None."""
        cols = list(columns)
        tr = current_request_trace()
        t0 = time.perf_counter() if tr is not None else 0.0
        got = self.cache.lookup_units([self._full(rg, c) for c in cols],
                                      count_misses=True)
        if tr is not None:
            tr.add_timed("result_probe", t0, time.perf_counter(), rg=rg,
                         columns=len(cols), hit=got is not None)
        if got is None:
            return None
        return {c: v for c, (v, _n) in zip(cols, got)}
