"""Request-lifecycle resilience primitives: cancel tokens, circuit
breakers, and the seeded chaos schedule.

PR 10's serve tier admitted a request and then owed it everything: no
end-to-end deadline, no way for the caller to take it back, and a
persistently-failing file re-paid its full retry cost for every request
that touched it.  This module holds the three small state machines that
close those gaps — deliberately free of serve/iostore imports so every
layer can use them without cycles:

- :class:`CancelToken` — one per request: an optional absolute deadline
  plus a caller-cancel flag.  ``check()`` is the unit-boundary gate the
  prefetch pipeline, the readers' sequential paths, and the IO retry loop
  all call; it raises the TYPED verdict
  (:class:`~tpu_parquet.errors.DeadlineExceededError` /
  :class:`~tpu_parquet.errors.CancelledError`) for that caller only.
- :class:`CircuitBreaker` / :class:`BreakerBoard` — per-file failure
  memory keyed by the :class:`~tpu_parquet.serve.PlanCache` generation
  key: N classified failures inside a window open the circuit, requests
  fast-fail with :class:`~tpu_parquet.errors.CircuitOpenError` naming the
  file and cooldown, a half-open probe closes it again.  One poisoned
  file can no longer drain every tenant's retry budget.
- :class:`ChaosSchedule` — a seeded, serializable plan of fault PHASES
  (stall storms, transient bursts, torn reads, per-file blackouts) over a
  read-ordinal axis, driving
  :class:`~tpu_parquet.iostore.FaultInjectingStore` through its
  ``_spec_for`` hook.  The whole resilience matrix — deadline expiry
  mid-storm, hedge wins under stall, circuit trips on a blacked-out file
  while healthy files complete — becomes a deterministic tier-1 test and
  a ``BENCH_SERVE_FAULTS`` bench section, zero network required.
"""

from __future__ import annotations

import random
import struct
import threading
import time
from dataclasses import dataclass

from .errors import (CancelledError, CircuitOpenError, DeadlineExceededError,
                     ParquetError)
from .obs import env_float, env_int

__all__ = [
    "BreakerBoard", "CancelToken", "ChaosPhase", "ChaosSchedule",
    "CircuitBreaker", "MAX_CHAOS_STALL_S", "PHASE_KINDS",
]


# ---------------------------------------------------------------------------
# cancel tokens: the per-request deadline + cancellation contract
# ---------------------------------------------------------------------------

class CancelToken:
    """Per-request cancellation + deadline state, checked at unit boundaries.

    ``deadline`` is an absolute ``time.monotonic()`` point (None = no
    deadline).  ``cancel(exc)`` flips the token from any thread; the next
    ``check()`` in the request's pipeline raises that exception (default: a
    :class:`~tpu_parquet.errors.CancelledError`).  An expired deadline
    raises :class:`~tpu_parquet.errors.DeadlineExceededError` — and LATCHES
    it, so every subsequent check in the same request reports the same
    verdict object (one request, one cause).

    Thread-safe and cheap on the hot path: an un-cancelled, deadline-less
    token's ``check()`` is two attribute reads.
    """

    __slots__ = ("deadline", "deadline_s", "_exc", "_lock", "_callbacks",
                 "trace")

    def __init__(self, deadline: "float | None" = None,
                 deadline_s: "float | None" = None):
        # deadline_s (the caller's relative budget) rides along purely for
        # the error message — the absolute point is what gets compared
        self.deadline = deadline
        self.deadline_s = deadline_s
        self._exc: "BaseException | None" = None
        self._lock = threading.Lock()
        self._callbacks: "list | None" = None
        # the request's RequestTrace rides the token — it already flows
        # from the serve tier through readers, prefetch workers, and both
        # iostores, so span sites guard on `token.trace is not None` and
        # pay nothing when tracing is off
        self.trace = None

    @classmethod
    def with_timeout(cls, seconds: "float | None") -> "CancelToken":
        """A token whose deadline is ``seconds`` from now (None = none)."""
        if seconds is None:
            return cls()
        return cls(deadline=time.monotonic() + float(seconds),
                   deadline_s=float(seconds))

    def cancel(self, exc: "BaseException | None" = None) -> None:
        """Flip the token: every subsequent ``check()`` raises ``exc``.
        First cause wins — a cancel landing after a deadline expiry (or a
        second cancel) never rewrites the verdict."""
        with self._lock:
            if self._exc is not None:
                return
            self._exc = exc if exc is not None else CancelledError(
                "request cancelled by caller")
            cbs, self._callbacks = self._callbacks, None
            verdict = self._exc
        self._fire(cbs, verdict)

    def on_cancel(self, callback) -> None:
        """Register ``callback(exc)`` to fire once when the token flips
        (cancel OR a deadline verdict latching in ``check()``); fires
        immediately if it already has.  Callbacks run outside the token
        lock and must not raise — a raising observer would steal the
        verdict from the request that owns it, so exceptions are
        swallowed.  Streaming sessions use this to deliver their terminal
        verdict to a blocked consumer promptly instead of at the next
        producer boundary."""
        with self._lock:
            exc = self._exc
            if exc is None:
                if self._callbacks is None:
                    self._callbacks = []
                self._callbacks.append(callback)
                return
        self._fire([callback], exc)

    @staticmethod
    def _fire(cbs, exc) -> None:
        for cb in cbs or ():
            try:
                cb(exc)
            except Exception:  # noqa: BLE001 — observers never own verdicts
                pass

    @property
    def cancelled(self) -> bool:
        return self._exc is not None

    def expired(self, now: "float | None" = None) -> bool:
        return (self.deadline is not None
                and (time.monotonic() if now is None else now)
                >= self.deadline)

    def remaining(self, now: "float | None" = None) -> "float | None":
        """Seconds left under the deadline (None = unbounded; floored at
        0.0 so callers can pass it straight to a wait timeout)."""
        if self.deadline is None:
            return None
        left = self.deadline - (time.monotonic() if now is None else now)
        return max(left, 0.0)

    def check(self) -> None:
        """The unit-boundary gate: raise the typed verdict if this request
        is cancelled or past its deadline; no-op otherwise."""
        exc = self._exc
        if exc is not None:
            raise exc
        if self.deadline is not None and time.monotonic() >= self.deadline:
            cbs = None
            with self._lock:
                if self._exc is None:
                    budget = (f" of {self.deadline_s:g}s"
                              if self.deadline_s is not None else "")
                    self._exc = DeadlineExceededError(
                        f"request deadline{budget} exceeded",
                        deadline_s=self.deadline_s)
                    cbs, self._callbacks = self._callbacks, None
                exc = self._exc
            self._fire(cbs, exc)
            raise exc


# ---------------------------------------------------------------------------
# circuit breakers: per-file failure memory
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """One file's breaker: closed → open after ``fails`` classified
    failures inside ``window_s`` → half-open after ``cooldown_s`` (ONE
    probe admitted) → closed on probe success, re-open on probe failure.

    Not thread-safe on its own — :class:`BreakerBoard` serializes access;
    the ``clock`` injection keeps the state machine unit-testable without
    sleeps.
    """

    __slots__ = ("fails", "window_s", "cooldown_s", "clock", "failures",
                 "opened_at", "probing", "probe_at", "state")

    def __init__(self, fails: int, window_s: float, cooldown_s: float,
                 clock=time.monotonic):
        self.fails = int(fails)
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self.failures: list[float] = []  # classified-failure timestamps
        self.opened_at = 0.0
        self.probing = False  # half-open: the one admitted probe is out
        self.probe_at = 0.0
        self.state = "closed"

    def admit(self) -> "float | None":
        """Gate one request: None = admitted; a float = fast-fail, that
        many seconds until the next half-open probe slot."""
        if self.state == "closed":
            return None
        now = self.clock()
        remaining = self.opened_at + self.cooldown_s - now
        if self.state == "open" and remaining <= 0:
            self.state = "half_open"
            self.probing = False
        if self.state == "half_open":
            # a probe that never reported (it died with an UNCLASSIFIED
            # error — deadline expiry, caller cancel — which deliberately
            # never calls note()) must not wedge the breaker open forever:
            # after a full cooldown of silence the probe slot is forfeit
            if self.probing and now - self.probe_at >= self.cooldown_s:
                self.probing = False
            if not self.probing:
                self.probing = True  # this caller IS the probe
                self.probe_at = now
                return None
            # a probe is already out: hold the line until it reports
            return max(self.probe_at + self.cooldown_s - now, 0.0) \
                or self.cooldown_s
        return max(remaining, 0.0)

    def note(self, ok: bool) -> "str | None":
        """Record a request outcome; returns the transition that happened
        (``"opened"`` / ``"reopened"`` / ``"closed"``) or None."""
        now = self.clock()
        if ok:
            self.failures.clear()
            if self.state != "closed":
                self.state = "closed"
                self.probing = False
                return "closed"
            return None
        if self.state == "half_open":
            # the probe failed: straight back to open, fresh cooldown
            self.state = "open"
            self.probing = False
            self.opened_at = now
            return "reopened"
        if self.state == "open":
            return None  # already open; in-flight stragglers don't re-trip
        self.failures.append(now)
        cutoff = now - self.window_s
        self.failures = [t for t in self.failures if t >= cutoff]
        if len(self.failures) >= self.fails:
            self.state = "open"
            self.opened_at = now
            self.failures.clear()
            return "opened"
        return None


class BreakerBoard:
    """The serve tier's breaker registry: one :class:`CircuitBreaker` per
    file generation key (the :class:`~tpu_parquet.serve.PlanCache` key, so
    a REWRITTEN file starts with a clean breaker), thread-safe, with the
    transition counters the registry ``serve.circuit`` section reports.

    Knobs (env-resolved once at construction): ``TPQ_CIRCUIT_FAILS``
    (default 5 classified failures), ``TPQ_CIRCUIT_WINDOW_S`` (default 30s
    sliding window), ``TPQ_CIRCUIT_COOLDOWN_S`` (default 5s before a
    half-open probe).  ``fails <= 0`` disables the board entirely.
    """

    def __init__(self, fails: "int | None" = None,
                 window_s: "float | None" = None,
                 cooldown_s: "float | None" = None, clock=time.monotonic):
        self.fails = (env_int("TPQ_CIRCUIT_FAILS", 5, lo=0)
                      if fails is None else int(fails))
        self.window_s = (env_float("TPQ_CIRCUIT_WINDOW_S", 30.0, lo=0.0)
                         if window_s is None else float(window_s))
        self.cooldown_s = (env_float("TPQ_CIRCUIT_COOLDOWN_S", 5.0, lo=0.0)
                           if cooldown_s is None else float(cooldown_s))
        self.clock = clock
        self._lock = threading.Lock()
        self._breakers: dict = {}  # key -> (CircuitBreaker, display name)
        self.opened = 0
        self.reopened = 0
        self.closed = 0
        self.fast_fails = 0

    @property
    def enabled(self) -> bool:
        return self.fails > 0

    def admit(self, key, name: str) -> None:
        """Gate one request's file: raises
        :class:`~tpu_parquet.errors.CircuitOpenError` naming the file and
        cooldown when its circuit is open."""
        if not self.enabled or key is None:
            return
        with self._lock:
            entry = self._breakers.get(key)
            if entry is None:
                return
            wait = entry[0].admit()
            if wait is None:
                return
            self.fast_fails += 1
        raise CircuitOpenError(
            f"circuit open for {name!r}: {self.fails} classified failures "
            f"within {self.window_s:g}s; next probe in {wait:.3f}s",
            file=name, retry_after_s=wait)

    def note(self, key, name: str, ok: bool) -> None:
        """Record one request's outcome against its file's breaker."""
        if not self.enabled or key is None:
            return
        with self._lock:
            entry = self._breakers.get(key)
            if entry is None:
                if ok:
                    return  # never create a breaker for a healthy file
                entry = self._breakers[key] = (
                    CircuitBreaker(self.fails, self.window_s,
                                   self.cooldown_s, clock=self.clock), name)
            transition = entry[0].note(ok)
            if transition == "opened":
                self.opened += 1
            elif transition == "reopened":
                self.reopened += 1
            elif transition == "closed":
                self.closed += 1
            # a closed breaker with no failure memory is dead weight —
            # drop it (whether the success closed an open circuit or just
            # wiped a closed one's failure window) so the board never
            # grows past the currently-failing set
            if ok and entry[0].state == "closed":
                self._breakers.pop(key, None)

    def open_files(self) -> "list[dict]":
        """The currently-open circuits, oldest first: ``{file,
        retry_after_s}`` — the doctor/autopsy ``circuit-open`` evidence."""
        now = self.clock()
        out = []
        with self._lock:
            for br, name in self._breakers.values():
                if br.state in ("open", "half_open"):
                    left = max(br.opened_at + br.cooldown_s - now, 0.0)
                    out.append({"file": name,
                                "retry_after_s": round(left, 3),
                                "state": br.state,
                                "opened_at": br.opened_at})
        out.sort(key=lambda d: d["opened_at"])
        for d in out:
            d.pop("opened_at")
        return out

    def counters(self) -> dict:
        """The registry ``serve.circuit`` subsection: transition flows +
        the ``open_now`` gauge + the open files' names."""
        open_entries = self.open_files()
        with self._lock:
            return {
                "opened": self.opened,
                "reopened": self.reopened,
                "closed": self.closed,
                "fast_fails": self.fast_fails,
                "open_now": len(open_entries),
                "open_files": [e["file"] for e in open_entries],
            }


# ---------------------------------------------------------------------------
# chaos schedule: seeded fault phases over a read-ordinal axis
# ---------------------------------------------------------------------------

PHASE_KINDS = ("stall", "transient", "torn", "blackout")
# planner invariant: no phase may stall longer than this per attempt — a
# schedule is a TEST plan, and an unbounded stall would turn a failing
# assertion into a hung suite
MAX_CHAOS_STALL_S = 5.0
# blob bounds (fuzz adoption rejects anything past them: a schedule is a
# few phases, not a DoS vector)
_MAX_PHASES = 64
_MAX_ORDINAL = 1 << 31
_CHAOS_MAGIC = b"TPQC"
_CHAOS_VERSION = 1
_PHASE_FMT = "<IIBBIf"  # start, end, kind, intensity, file_index+1, stall_s


def _f32(x: float) -> float:
    """Round a float through the blob's f32 representation."""
    return struct.unpack("<f", struct.pack("<f", x))[0]


@dataclass(frozen=True)
class ChaosPhase:
    """One fault phase over the read-ordinal axis: fetches whose global
    ordinal lands in ``[start, end)`` see the fault.

    - ``kind``        one of :data:`PHASE_KINDS`;
    - ``intensity``   attempts affected per range (``fail_first``-style:
      the first N attempts at an offset fault, then heal — except
      ``blackout``, which never heals);
    - ``file_index``  which opened file the phase applies to (-1 = all) —
      the per-file blackout that trips exactly one circuit;
    - ``stall_s``     per-attempt stall bound for ``stall`` phases, capped
      at :data:`MAX_CHAOS_STALL_S`.
    """

    start: int
    end: int
    kind: str
    intensity: int = 1
    file_index: int = -1
    stall_s: float = 0.25


class ChaosSchedule:
    """A seeded, serializable plan of fault phases (the chaos harness).

    Invariants (validated on construction AND on blob adoption — the fuzz
    target's contract): phases sorted by ``start``, pairwise DISJOINT,
    ``end > start``, kinds known, intensities in [1, 255], stalls bounded
    by :data:`MAX_CHAOS_STALL_S`, at most ``_MAX_PHASES`` phases.  Equality
    is structural, and ``from_blob(to_blob(s)) == s`` exactly — the
    round-trip determinism the fuzz target asserts.
    """

    def __init__(self, phases, seed: int = 0):
        # stall_s travels as an f32 in the blob: quantize at construction
        # so from_blob(to_blob(s)) == s holds for ANY schedule, not only
        # ones that already round-tripped once
        self.phases = tuple(
            p if p.stall_s == _f32(p.stall_s)
            else ChaosPhase(p.start, p.end, p.kind, p.intensity,
                            p.file_index, _f32(p.stall_s))
            for p in phases)
        self.seed = int(seed)
        self.validate()

    # -- invariants -----------------------------------------------------------

    def validate(self) -> None:
        if len(self.phases) > _MAX_PHASES:
            raise ParquetError(
                f"chaos schedule has {len(self.phases)} phases "
                f"(max {_MAX_PHASES})")
        prev_end = None
        for p in self.phases:
            if p.kind not in PHASE_KINDS:
                raise ParquetError(f"unknown chaos phase kind {p.kind!r}")
            if not (0 <= p.start < p.end <= _MAX_ORDINAL):
                raise ParquetError(
                    f"chaos phase range [{p.start}, {p.end}) is invalid")
            if prev_end is not None and p.start < prev_end:
                raise ParquetError(
                    f"chaos phases overlap at ordinal {p.start} "
                    f"(previous phase ends at {prev_end})")
            if not (1 <= p.intensity <= 255):
                raise ParquetError(
                    f"chaos phase intensity {p.intensity} out of [1, 255]")
            if p.kind == "stall" and not (
                    0.0 < p.stall_s <= MAX_CHAOS_STALL_S):
                raise ParquetError(
                    f"chaos stall_s {p.stall_s!r} out of "
                    f"(0, {MAX_CHAOS_STALL_S}] — unbounded stalls are "
                    f"banned by design")
            if p.file_index < -1 or p.file_index >= (1 << 16):
                raise ParquetError(
                    f"chaos phase file_index {p.file_index} out of range")
            prev_end = p.end

    def __eq__(self, other) -> bool:
        return (isinstance(other, ChaosSchedule)
                and self.seed == other.seed
                and self.phases == other.phases)

    def __hash__(self):
        return hash((self.seed, self.phases))

    # -- generation -----------------------------------------------------------

    @classmethod
    def generate(cls, seed: int, n_phases: int = 4, horizon: int = 256,
                 files: int = 1) -> "ChaosSchedule":
        """A deterministic schedule from a seed: ``n_phases`` disjoint
        phases spread over ``[0, horizon)`` read ordinals, kinds and
        intensities drawn from a seeded PRNG.  Same seed, same schedule —
        byte for byte (the fuzz target proves it)."""
        rng = random.Random(int(seed) & 0xFFFFFFFF)
        n = max(min(int(n_phases), _MAX_PHASES), 0)
        horizon = max(int(horizon), 2 * n or 2)
        # cut the horizon into 2n slots, every other slot a phase: disjoint
        # by construction, with healthy gaps between storms
        edges = sorted(rng.sample(range(horizon), 2 * n)) if n else []
        phases = []
        for i in range(n):
            start, end = edges[2 * i], edges[2 * i + 1]
            if end <= start:
                continue
            kind = rng.choice(PHASE_KINDS)
            phases.append(ChaosPhase(
                start=start, end=end, kind=kind,
                intensity=rng.randint(1, 3),
                file_index=rng.randrange(files) if (
                    kind == "blackout" and files > 0) else -1,
                stall_s=round(rng.uniform(0.05, 0.5), 3),
            ))
        return cls(phases, seed=seed)

    # -- serialization --------------------------------------------------------

    def to_blob(self) -> bytes:
        out = bytearray(_CHAOS_MAGIC)
        out.append(_CHAOS_VERSION)
        out += struct.pack("<IH", self.seed & 0xFFFFFFFF, len(self.phases))
        for p in self.phases:
            out += struct.pack(
                _PHASE_FMT, p.start, p.end, PHASE_KINDS.index(p.kind),
                p.intensity, p.file_index + 1, p.stall_s)
        return bytes(out)

    @classmethod
    def from_blob(cls, blob: bytes) -> "ChaosSchedule":
        """Adopt a serialized schedule; raises
        :class:`~tpu_parquet.errors.ParquetError` for anything malformed
        (truncation, bad magic, unknown kinds, invariant violations) — the
        fuzz oracle's single-type contract."""
        blob = bytes(blob)
        head = 4 + 1 + struct.calcsize("<IH")
        if len(blob) < head or blob[:4] != _CHAOS_MAGIC:
            raise ParquetError("chaos schedule blob: bad magic or truncated")
        if blob[4] != _CHAOS_VERSION:
            raise ParquetError(
                f"chaos schedule blob: unknown version {blob[4]}")
        seed, n = struct.unpack_from("<IH", blob, 5)
        psize = struct.calcsize(_PHASE_FMT)
        if len(blob) != head + n * psize:
            raise ParquetError(
                f"chaos schedule blob: {len(blob)} bytes for {n} phases "
                f"(want {head + n * psize})")
        phases = []
        for i in range(n):
            start, end, kind_i, intensity, fidx, stall_s = struct.unpack_from(
                _PHASE_FMT, blob, head + i * psize)
            if kind_i >= len(PHASE_KINDS):
                raise ParquetError(
                    f"chaos schedule blob: unknown phase kind {kind_i}")
            if not (stall_s == stall_s):  # NaN smuggled through the float
                raise ParquetError("chaos schedule blob: stall_s is NaN")
            phases.append(ChaosPhase(
                start=start, end=end, kind=PHASE_KINDS[kind_i],
                intensity=intensity, file_index=fidx - 1,
                stall_s=stall_s))  # already exact f32 from the unpack
        return cls(phases, seed=seed)

    # -- driving FaultInjectingStore ------------------------------------------

    def phase_at(self, ordinal: int,
                 file_index: int = -1) -> "ChaosPhase | None":
        """The phase covering ``ordinal`` for ``file_index`` (phases are
        sorted + disjoint, so at most one matches)."""
        for p in self.phases:
            if p.start <= ordinal < p.end and (
                    p.file_index == -1 or p.file_index == file_index):
                return p
            if p.start > ordinal:
                break
        return None

    def store_factory(self, paths, config=None, inner_factory=None):
        """A ``store=`` factory driving the schedule over a scan's files.

        ``paths`` orders the files (the ``file_index`` axis); each opened
        file gets a :class:`~tpu_parquet.iostore.FaultInjectingStore` whose
        per-fetch :class:`~tpu_parquet.iostore.FaultSpec` comes from the
        phase covering a SHARED read-ordinal counter — one clock for the
        whole scan, so a stall storm hits every file at once while a
        blackout stays pinned to its one victim.  ``release()`` on the
        returned factory's ``.stores`` unblocks injected stalls in
        teardown.
        """
        import os

        from .iostore import FaultInjectingStore, LocalStore

        index_of = {os.path.abspath(os.fspath(p)): i
                    for i, p in enumerate(paths)}
        counter = _OrdinalClock()
        schedule = self

        class _ChaosStore(FaultInjectingStore):
            """FaultInjectingStore whose spec is phase-driven: the chaos
            schedule IS the spec provider (see ``_spec_for``)."""

            def __init__(self, inner, file_index: int):
                super().__init__(inner, config=config, seed=schedule.seed)
                self._file_index = file_index

            def _spec_for(self, offset, size, attempt):
                from .iostore import FaultSpec

                phase = schedule.phase_at(counter.tick(), self._file_index)
                if phase is None:
                    return FaultSpec()  # healthy: clean passthrough
                if phase.kind == "stall":
                    return FaultSpec(stall_first=phase.intensity,
                                     stall_s=phase.stall_s)
                if phase.kind == "transient":
                    return FaultSpec(fail_first=phase.intensity)
                if phase.kind == "torn":
                    return FaultSpec(torn_first=phase.intensity)
                # blackout: every attempt fails until the phase ends — the
                # circuit breaker's trip wire
                return FaultSpec(fail_first=1 << 30)

        stores: list = []

        def factory(f):
            path = os.path.abspath(getattr(f, "name", "") or "")
            inner = (inner_factory(f) if inner_factory is not None
                     else LocalStore(f))
            st = _ChaosStore(inner, index_of.get(path, -1))
            stores.append(st)
            return st

        factory.stores = stores
        factory.release = lambda: [s.release() for s in stores]
        return factory


class _OrdinalClock:
    """The shared read-ordinal counter a chaos run advances on every
    injected-store fetch attempt (thread-safe; deterministic per-file when
    the test drives one file at a time, monotonic always)."""

    __slots__ = ("_n", "_lock")

    def __init__(self):
        self._n = 0
        self._lock = threading.Lock()

    def tick(self) -> int:
        with self._lock:
            n = self._n
            self._n += 1
            return n
