"""FileWriter: the low-level write API.

Equivalent of the reference's file_writer.go FileWriter (options :41-154, AddData
:280-295, FlushRowGroup :229-276, Close :297-350) — with a columnar batch path
(`write_columns`) as the primary TPU-native entry point and row-map writes
(`write_row`, AddData parity) layered on the shredder.

Layout discipline mirrors the reference: "PAR1" magic first, row groups flushed
incrementally (size-triggered or explicit), footer thrift + length + magic at
close.  Row-group/column key-value metadata via flush options (file_writer.go:
156-226).
"""

from __future__ import annotations

import os
from typing import BinaryIO, Optional, Sequence, Union

import numpy as np

from .chunk_encode import ChunkEncoder, DEFAULT_PAGE_SIZE
from .column import ByteArrayData, ColumnData
from .footer import MAGIC, serialize_footer
from .footer import ParquetError
from .format import (
    ColumnOrder,
    CompressionCodec,
    Encoding,
    FileMetaData,
    KeyValue,
    RowGroup,
    Type,
    TypeDefinedOrder,
)
from .schema.core import Schema, SchemaNode
from .shred import Shredder, _coerce_values
from . import __version__

DEFAULT_ROW_GROUP_SIZE = 128 << 20  # 128 MiB, file_writer.go default
DEFAULT_CREATED_BY = f"tpu-parquet version {__version__}"

_CRC_ON = ("1", "on", "true", "crc", "yes")
_CRC_OFF = ("0", "off", "false", "no")


def resolve_write_crc(write_crc=None) -> bool:
    """Resolve a writer's ``write_crc`` option to a bool.

    ``None`` (the default) resolves through ``TPQ_WRITE_CRC``, whose
    default is ON — mirroring the reader's default-on ``TPQ_VALIDATE``
    contract: validation is default-on, so freshly written files must
    carry the CRCs the cheap integrity tier verifies, or the tier
    silently covers nothing.  Explicit ``False``/``"off"`` opts out;
    kwarg strings are strict, a malformed env degrades to the default
    with one warning (the same discipline as ``resolve_validate``).
    """
    if write_crc is None:
        from .obs import warn_env_once

        raw = os.environ.get("TPQ_WRITE_CRC", "1").strip().lower()
        if raw in _CRC_ON:
            return True
        if raw in _CRC_OFF:
            return False
        warn_env_once("TPQ_WRITE_CRC", raw, "1 (CRCs written)")
        return True
    if isinstance(write_crc, bool):
        return write_crc
    v = str(write_crc).strip().lower()
    if v in _CRC_ON:
        return True
    if v in _CRC_OFF:
        return False
    raise ValueError(
        f"write_crc must be a bool, 'on', or 'off'; got {write_crc!r}")


class FileWriter:
    """Low-level parquet writer.

    Options (file_writer.go parity): ``codec`` (WithCompressionCodec),
    ``row_group_size`` (WithMaxRowGroupSize, size-triggered auto-flush),
    ``page_size`` (WithMaxPageSize), ``data_page_version`` (WithDataPageV2),
    ``write_crc`` (WithCRC), ``created_by`` (WithCreator), ``kv_metadata``
    (WithMetaData), ``use_dictionary``, per-column ``column_encodings``.
    """

    def __init__(
        self,
        sink: Union[str, os.PathLike, BinaryIO],
        schema: Schema,
        codec: int = CompressionCodec.SNAPPY,
        row_group_size: int = DEFAULT_ROW_GROUP_SIZE,
        page_size: int = DEFAULT_PAGE_SIZE,
        data_page_version: int = 1,
        use_dictionary: bool = True,
        write_crc: "Optional[bool]" = None,
        write_statistics: bool = True,
        created_by: str = DEFAULT_CREATED_BY,
        kv_metadata: Optional[dict] = None,
        column_encodings: Optional[dict] = None,
        stats=None,
    ):
        if isinstance(sink, (str, os.PathLike)):
            self._f: BinaryIO = open(sink, "wb")
            self._owns_file = True
        else:
            self._f = sink
            self._owns_file = False
        self.schema = schema
        self.codec = int(codec)
        self.row_group_size = row_group_size
        self.page_size = page_size
        self.data_page_version = data_page_version
        self.use_dictionary = use_dictionary
        # None resolves via TPQ_WRITE_CRC (default ON): the reader's
        # integrity tier validates CRCs by default, so the writer writes
        # them by default — the two knobs mirror each other
        self.write_crc = resolve_write_crc(write_crc)
        self.write_statistics = write_statistics
        # optional write-side observability (write.WriteStats): encode/
        # compress/flush lane seconds + row counters for the registry
        # `write` section — pq_tool doctor's slow-write attribution
        self.stats = stats
        self.created_by = created_by
        self.kv_metadata = dict(kv_metadata or {})
        self.column_encodings = {
            tuple(k.split(".")) if isinstance(k, str) else tuple(k): Encoding(v)
            for k, v in (column_encodings or {}).items()
        }

        if self.stats is not None:
            self.stats.touch_wall()  # the writer's wall spans open..close
        self._shredder = Shredder(schema)
        self._row_groups: list[RowGroup] = []
        self._pending_cols: Optional[dict[str, ColumnData]] = None
        self._pending_rows = 0
        self._total_rows = 0
        self._pos = 0
        self._closed = False
        self._write(MAGIC)

    # -- plumbing -------------------------------------------------------------

    def _write(self, data: bytes) -> None:
        self._f.write(data)
        self._pos += len(data)

    @property
    def current_file_size(self) -> int:
        """Bytes written so far (CurrentFileSize parity, footer excluded)."""
        return self._pos

    @property
    def current_row_group_size(self) -> int:
        """Estimated in-memory size of the pending row group."""
        est = self._shredder.est_bytes
        if self._pending_cols:
            for cd in self._pending_cols.values():
                if isinstance(cd.values, ByteArrayData):
                    est += int(cd.values.offsets[-1]) + 4 * len(cd.values)
                else:
                    est += cd.values.nbytes
                est += cd.num_leaf_slots
        return est

    # -- row-oriented writes (AddData parity) ----------------------------------

    def write_row(self, row: dict) -> None:
        """Shred one nested dict row (raw physical or logical LIST/MAP shape)."""
        self._check_open()
        if self._pending_cols is not None:
            # switching from columnar to row writes: flush to keep row order
            self.flush_row_group()
        self._shredder.add_row(row)
        self._pending_rows += 1
        if self.current_row_group_size >= self.row_group_size:
            self.flush_row_group()

    def write_rows(self, rows) -> None:
        for row in rows:
            self.write_row(row)

    # -- columnar writes (the TPU-native path) ---------------------------------

    def write_columns(self, columns: dict, num_rows: Optional[int] = None) -> None:
        """Write a columnar batch: {dotted_path: array-like | ColumnData}.

        Flat required columns may be plain numpy arrays/lists; nullable or
        nested columns must be ColumnData with def/rep levels.
        """
        self._check_open()
        batch: dict[str, ColumnData] = {}
        batch_rows = None
        for leaf in self.schema.leaves:
            name = ".".join(leaf.path)
            if name not in columns:
                raise ParquetError(f"write_columns missing column {name!r}")
            v = columns[name]
            cd = self._as_column_data(v, leaf)
            rows_here = (
                int(np.count_nonzero(cd.rep_levels == 0))
                if cd.rep_levels is not None
                else cd.num_leaf_slots
            )
            if batch_rows is None:
                batch_rows = rows_here
            elif batch_rows != rows_here:
                raise ParquetError(
                    f"column {name}: {rows_here} rows, expected {batch_rows}"
                )
            batch[name] = cd
        if num_rows is not None and batch_rows != num_rows:
            raise ParquetError(f"batch has {batch_rows} rows, declared {num_rows}")
        if self._shredder.num_rows:
            # switching from row to columnar writes: flush to keep row order
            self.flush_row_group()
        if self._pending_cols is None:
            self._pending_cols = batch
        else:
            from .reader import _concat_column_data

            self._pending_cols = {
                k: _concat_column_data([self._pending_cols[k], batch[k]])
                for k in self._pending_cols
            }
        self._pending_rows += batch_rows or 0
        if self.current_row_group_size >= self.row_group_size:
            self.flush_row_group()

    def _as_column_data(self, v, leaf: SchemaNode) -> ColumnData:
        if isinstance(v, ColumnData):
            if v.max_def != leaf.max_def or v.max_rep != leaf.max_rep:
                raise ParquetError(
                    f"column {leaf.flat_name()}: ColumnData levels "
                    f"({v.max_rep},{v.max_def}) don't match schema "
                    f"({leaf.max_rep},{leaf.max_def})"
                )
            return v
        if leaf.max_rep > 0:
            raise ParquetError(
                f"column {leaf.flat_name()}: nested columns need ColumnData"
            )
        if isinstance(v, ByteArrayData):
            vals = v
        elif isinstance(v, np.ndarray) and leaf.physical_type not in (
            Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY,
        ):
            vals = v
        else:
            vals = _coerce_values(list(v), leaf)
        n = len(vals)
        if leaf.max_def > 0:
            return ColumnData(
                values=vals,
                def_levels=np.full(n, leaf.max_def, dtype=np.int32),
                max_def=leaf.max_def, max_rep=0, num_leaf_slots=n,
            )
        return ColumnData(values=vals, max_def=0, max_rep=0, num_leaf_slots=n)

    # -- flush / close ---------------------------------------------------------

    def flush_row_group(
        self,
        kv_metadata: Optional[dict] = None,
        column_kv_metadata: Optional[dict] = None,
    ) -> None:
        """Serialize pending data as one row group (FlushRowGroup parity; the
        kv options mirror WithRowGroupMetaData(ForColumn), file_writer.go:193-226)."""
        self._check_open()
        cols = self._pending_cols or {}
        if self._shredder.num_rows:
            shredded, _n = self._shredder.harvest()
            cols = shredded if not cols else cols
        num_rows = self._pending_rows
        if num_rows == 0 and not cols:
            return  # nothing pending (reference: flushing empty group is a no-op
                    # unless the file would otherwise have no groups)
        chunks = []
        total_bytes = 0
        total_comp = 0
        for leaf in self.schema.leaves:
            name = ".".join(leaf.path)
            cd = cols.get(name)
            if cd is None:
                raise ParquetError(f"row group missing column {name}")
            enc = ChunkEncoder(
                leaf,
                codec=self.codec,
                page_size=self.page_size,
                data_page_version=self.data_page_version,
                use_dictionary=self.use_dictionary,
                write_crc=self.write_crc,
                encoding=self.column_encodings.get(leaf.path),
                write_statistics=self.write_statistics,
                stats=self.stats,
            )
            res = enc.write(cd, self._f, self._pos)
            self._pos += res.total_compressed
            md = res.chunk.meta_data
            if column_kv_metadata and name in column_kv_metadata:
                md.key_value_metadata = [
                    KeyValue(key=k, value=v)
                    for k, v in column_kv_metadata[name].items()
                ]
            chunks.append(res.chunk)
            total_bytes += res.total_uncompressed
            total_comp += res.total_compressed
        rg = RowGroup(
            columns=chunks,
            total_byte_size=total_bytes,
            num_rows=num_rows,
            total_compressed_size=total_comp,
            file_offset=chunks[0].meta_data.dictionary_page_offset
            if chunks and chunks[0].meta_data.dictionary_page_offset is not None
            else (chunks[0].meta_data.data_page_offset if chunks else self._pos),
            ordinal=len(self._row_groups),
        )
        if kv_metadata:
            # row-group kv metadata is not part of the thrift RowGroup; the
            # reference stores it in the file-level kv list namespaced by group
            for k, v in kv_metadata.items():
                self.kv_metadata[f"rowgroup.{len(self._row_groups)}.{k}"] = v
        self._row_groups.append(rg)
        self._total_rows += num_rows
        self._pending_cols = None
        self._pending_rows = 0
        if self.stats is not None:
            self.stats.count_row_group(num_rows, chunks=len(chunks))
            self.stats.touch_wall()

    def close(self) -> None:
        if self._closed:
            return
        if self._pending_rows or self._shredder.num_rows or self._pending_cols:
            self.flush_row_group()
        meta = FileMetaData(
            version=1,
            schema=self.schema.to_flat_elements(),
            num_rows=self._total_rows,
            row_groups=self._row_groups,
            created_by=self.created_by,
            key_value_metadata=[
                KeyValue(key=k, value=v) for k, v in self.kv_metadata.items()
            ]
            or None,
            column_orders=[
                ColumnOrder(TYPE_ORDER=TypeDefinedOrder())
                for _ in self.schema.leaves
            ],
        )
        footer = serialize_footer(meta)
        if self.stats is not None:
            with self.stats.timed("flush", nbytes=len(footer)):
                self._write(footer)
            self.stats.touch_wall()
        else:
            self._write(footer)
        if self._owns_file:
            self._f.close()
        self._closed = True

    def _check_open(self):
        if self._closed:
            raise ParquetError("writer is closed")

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None:
            self.close()
        elif self._owns_file:
            self._f.close()
        return False


def corrupt_page(path, row_group: int = 0, column=0, page: int = 0,
                 mode: str = "bitflip", seed: int = 0) -> tuple[int, int]:
    """Deterministically corrupt ONE page's payload of a written file,
    in place — the writer-side test helper behind the corrupt-unit fault
    matrix (tests, fuzz target #15, bench ``data_faults``).

    ``column`` is a leaf ordinal or dotted name; ``page`` a data-page
    ordinal within the chunk (``-1`` corrupts the dictionary page).  The
    corruption is :func:`tpu_parquet.quarantine.corrupt_bytes` over the
    page's COMPRESSED payload — length-preserving, so the file still
    parses structurally and the integrity tier (CRC when written,
    decode-time sanity otherwise) is what must catch it.  Returns the
    corrupted span's absolute ``(offset, length)``.
    """
    from .chunk_decode import validate_chunk_meta, walk_pages
    from .footer import read_file_metadata
    from .format import PageType
    from .quarantine import corrupt_bytes
    from .schema.core import Schema

    with open(path, "r+b") as f:
        md = read_file_metadata(f)
        schema = Schema.from_file_metadata(md)
        leaves = schema.leaves
        if isinstance(column, str):
            want = tuple(column.split("."))
            idx = next((i for i, l in enumerate(leaves) if l.path == want),
                       None)
            if idx is None:
                raise KeyError(f"no such column {column!r}")
            column = idx
        leaf = leaves[column]
        rg = md.row_groups[row_group]
        chunk = next(
            c for c in rg.columns
            if c.meta_data is not None
            and tuple(c.meta_data.path_in_schema or ()) == leaf.path)
        cmd, offset = validate_chunk_meta(chunk, leaf)
        f.seek(offset)
        buf = f.read(cmd.total_compressed_size)
        data_pages, dict_page = [], None
        for ps in walk_pages(buf, cmd.num_values):
            if ps.header.type == PageType.DICTIONARY_PAGE:
                dict_page = ps
            elif ps.header.type in (PageType.DATA_PAGE,
                                    PageType.DATA_PAGE_V2):
                data_pages.append(ps)
        ps = dict_page if page == -1 else data_pages[page]
        if ps is None:
            raise IndexError("chunk has no dictionary page")
        payload = buf[ps.payload_start : ps.payload_end]
        bad = corrupt_bytes(bytes(payload), mode, seed)
        f.seek(offset + ps.payload_start)
        f.write(bad)
    return offset + ps.payload_start, len(bad)
